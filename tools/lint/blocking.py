"""Deadline & blocking-call discipline: request-path liveness analysis.

PR 8 gave every request one ``Deadline``; PR 15 put a synchronous
network ship on the ingest ack path.  The contract that makes those
safe is a liveness property no test can pin exhaustively: a blocking
primitive reachable from a request-serving entry point must derive its
bound from the deadline's remainder (or a config timeout key, or a
``min()`` clamp over one of those), and must never block while holding
a lock another request contends.  Two analyzers enforce it over the
PR 3 call graph:

  deadline_discipline
    blocking-unbounded   a cataloged blocking primitive (HTTP client
                         call, socket connect/recv without settimeout,
                         lock.acquire() with no timeout, blocking
                         queue.get/put, unbounded Thread.join, Event/
                         Condition wait without timeout, subprocess
                         wait) reachable from a request-serving entry
                         point whose bound does NOT evaluate to a
                         sanctioned source.
    blocking-sleep       `time.sleep` on a request path — even a short
                         constant sleep cannot observe the deadline's
                         cancellation token; use
                         `Deadline.wait_cancelled` or a bounded
                         condition wait instead.

  hold_lock_while_blocking
    hold-lock-while-blocking   a cataloged blocking call executed
                         inside `with self.<lock>:` where <lock> is
                         named by at least one `# guarded-by:`
                         annotation, on a request path — the class of
                         bug where one wedged peer freezes every
                         request contending the same lock.
                         `Condition.wait` is exempt (it releases the
                         lock while waiting).

Sanctioned bound sources (recognition mirrors taint's sanitizers —
optimistic: a site is clean when ANY assignment path bounds it, and
the statement walk is resource_leak-style so an early return that
crosses the site BEFORE the clamp still reports):

  * a numeric literal or module-level numeric constant
  * `deadline.remaining_ms()` / `.remaining` — deadline-derived
  * a config getter whose key names a timeout-ish quantity
    (`cfg.get_int("tsd.replication.ship_timeout_ms")`)
  * an instance attribute initialized from one of the above
  * `min(...)` with at least one bounded arm; `max(...)`/arithmetic
    over all-bounded operands; a repo function whose every return
    evaluates bounded

Justified sites the analyzer cannot see through carry a
`# blocking: bounded-by <reason>` annotation (grammar shared with
tsdbsan in tools/lint/annotations.py); suppressions, SARIF, baseline
and --changed-only all inherit from the runner.

Entry points — the request-serving surface: any method named like an
rpc handler (`execute_http`, `handle_telnet`, ...), everything in the
planner/batcher/cluster/admission modules, and the replication
ship-before-ack route (`on_committed` / `ingest_bulk` /
`route_point`).  The puller/catch-up side of replication is a
background cadence, not a request path.  Fixture/test scopes override
all of these through `ctx.bucket("blocking")`.
"""

from __future__ import annotations

import ast
import re

from tools.lint.annotations import (ClassAnnotations, blocking_annotation,
                                    self_attr as _self_attr)
from tools.lint.astindex import get_ast_index
from tools.lint.callgraph import get_callgraph, module_name
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_UNBOUNDED = "blocking-unbounded"
RULE_SLEEP = "blocking-sleep"
RULE_HOLD = "hold-lock-while-blocking"

BLOCKING_DIRS = ("opentsdb_tpu/",)

# Request-serving entry points, three ways (all bucket-overridable):
# by method NAME (rpc dispatch `handler.execute_http(...)` is beyond
# devirtualization — too many implementers — so the handler surface is
# identified by its naming convention), by whole-module prefix, and by
# exact qname for the replication ack route.
ENTRY_METHODS = frozenset({
    "execute_http", "execute_telnet", "execute_telnet_batch",
    "handle_http", "handle_telnet", "handle_telnet_batch",
})
ENTRY_PREFIXES = (
    "opentsdb_tpu.query.planner.",
    "opentsdb_tpu.query.batcher.",
    "opentsdb_tpu.tsd.cluster.",
    "opentsdb_tpu.tsd.admission.",
)
ENTRY_QNAMES = frozenset({
    "opentsdb_tpu.tsd.replication.ReplicationManager.route_point",
    "opentsdb_tpu.tsd.replication.ReplicationManager.ingest_bulk",
    "opentsdb_tpu.tsd.replication.ReplicationManager.on_committed",
})

# Receiver constructor name -> blocking-relevant type tag.
_CTOR_TAGS = {
    "Lock": "lock", "RLock": "lock", "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Queue": "queue", "SimpleQueue": "queue", "LifoQueue": "queue",
    "PriorityQueue": "queue", "JoinableQueue": "queue",
    "Thread": "thread", "Timer": "thread",
    "Condition": "condition", "Event": "event", "Barrier": "event",
    "Popen": "popen",
    "socket": "socket", "create_connection": "socket",
}

# Socket methods that block on the peer once connected.
_SOCKET_BLOCKERS = frozenset({"connect", "recv", "recv_into", "sendall",
                              "send", "accept", "makefile", "recvfrom"})

# Deadline-derived bound methods (opentsdb_tpu/query/limits.py).
_DEADLINE_METHODS = frozenset({"remaining_ms", "remaining_s", "remaining",
                               "wait_cancelled"})

# Config keys that name a wall-clock quantity.  A getter call with a
# matching literal key is a sanctioned bound source (the config schema
# analyzer separately guarantees the key exists).
_TIMEOUT_KEY = re.compile(
    r"timeout|interval|deadline|budget|delay|tick|ttl|period|_ms$|_s$")
_CONFIG_GETTERS = frozenset({"get_int", "get_float"})

_SLEEP_HINT = ("it cannot observe the request deadline's cancellation "
               "token; use Deadline.wait_cancelled / a bounded condition "
               "wait, or annotate '# blocking: bounded-by <reason>'")
_UNBOUNDED_HINT = ("derives no bound from the deadline's remainder, a "
                   "config timeout key, or a min() clamp; pass a bounded "
                   "timeout or annotate '# blocking: bounded-by <reason>'")


class _Site:
    """One cataloged blocking call: where, what, how bounded."""

    __slots__ = ("line", "kind", "label", "bounded", "held", "annotated")

    def __init__(self, line: int, kind: str, label: str, bounded: bool,
                 held: frozenset, annotated: bool):
        self.line = line
        self.kind = kind            # sleep | http | socket | lock | ...
        self.label = label          # human label for the message
        self.bounded = bounded
        self.held = held            # lock attrs held at the call
        self.annotated = annotated


class _FnScan:
    """Blocking sites + outgoing call edges of one function, collected
    by a resource_leak-style statement walk: the bound environment at
    each site is the one at that PROGRAM POINT, so an early return past
    the clamp leaves the pre-clamp (unbounded) verdict in place."""

    def __init__(self, fi, src: SourceFile, analysis: "_Analysis",
                 cls: ClassAnnotations | None, is_thread_class: bool):
        self.fi = fi
        self.src = src
        self.an = analysis
        self.cls = cls
        self.is_thread_class = is_thread_class
        self.sites: list[_Site] = []
        self.callees: set[str] = set()
        self.env: dict[str, bool] = {}       # local name -> bounded
        self.local_types: dict[str, str] = {}  # local name -> type tag
        self.sock_timeout: set[str] = set()  # socket names settimeout'd

    # -- entry ------------------------------------------------------------

    def run(self) -> None:
        self._walk(self.fi.node.body, frozenset())

    # -- receiver typing --------------------------------------------------

    def _recv_tag(self, expr) -> str | None:
        if isinstance(expr, ast.Name):
            return self.local_types.get(expr.id)
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            if attr in self.cls.locks:
                return "lock"
            ctor = self.cls.attr_types.get(attr)
            if ctor is not None:
                return _CTOR_TAGS.get(ctor)
        return None

    @staticmethod
    def _ctor_tag(expr) -> str | None:
        if not isinstance(expr, ast.Call):
            return None
        f = expr.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        return _CTOR_TAGS.get(name) if name else None

    # -- bound evaluation -------------------------------------------------

    def _bounded(self, expr) -> bool:
        return self.an.eval_bound(expr, self.env, self.cls, self.fi)

    def _arg(self, call: ast.Call, kw: str, pos: int | None):
        for k in call.keywords:
            if k.arg == kw:
                return k.value
        if pos is not None and len(call.args) > pos:
            a = call.args[pos]
            return a.value if isinstance(a, ast.Starred) else a
        return None

    @staticmethod
    def _is_false(expr) -> bool:
        return isinstance(expr, ast.Constant) and expr.value is False

    # -- the catalog ------------------------------------------------------

    def _match(self, call: ast.Call, held: frozenset) -> None:
        f = call.func
        mod = self.an.graph.modules.get(self.fi.module)
        imports = mod.imports if mod is not None else {}
        kind = label = None
        bound = None          # the timeout expression, if any
        nonblocking = False
        if isinstance(f, ast.Name):
            tgt = imports.get(f.id, "")
            if f.id == "sleep" and tgt == "time.sleep":
                kind, label = "sleep", "time.sleep"
            elif f.id == "urlopen" or tgt.endswith(".urlopen"):
                kind, label = "http", "HTTP call"
                bound = self._arg(call, "timeout", 2)
            elif f.id == "create_connection" \
                    or tgt == "socket.create_connection":
                kind, label = "socket", "socket connect"
                bound = self._arg(call, "timeout", 1)
        elif isinstance(f, ast.Attribute):
            base = f.value
            dotted = None
            if isinstance(base, ast.Name):
                dotted = imports.get(base.id, base.id)
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name):
                dotted = "%s.%s" % (base.value.id, base.attr)
            if dotted == "time" and f.attr == "sleep":
                kind, label = "sleep", "time.sleep"
            elif f.attr == "urlopen" and dotted in (
                    "urllib.request", "request", "urllib2"):
                kind, label = "http", "HTTP call"
                bound = self._arg(call, "timeout", 2)
            elif dotted == "socket" and f.attr == "create_connection":
                kind, label = "socket", "socket connect"
                bound = self._arg(call, "timeout", 1)
            elif dotted == "subprocess" and f.attr in (
                    "run", "call", "check_call", "check_output"):
                kind, label = "subprocess", "subprocess %s" % f.attr
                bound = self._arg(call, "timeout", None)
            else:
                tag = self._recv_tag(base)
                if tag == "socket" and f.attr == "settimeout":
                    a = self._arg(call, "value", 0)
                    if isinstance(base, ast.Name) and a is not None \
                            and self._bounded(a):
                        self.sock_timeout.add(base.id)
                    return
                if tag == "socket" and f.attr in _SOCKET_BLOCKERS:
                    kind, label = "socket", "socket.%s" % f.attr
                    name = base.id if isinstance(base, ast.Name) else None
                    if name in self.sock_timeout:
                        bound = ast.Constant(value=1)    # settimeout'd
                elif tag == "lock" and f.attr == "acquire":
                    kind, label = "lock", "lock.acquire"
                    blocking = self._arg(call, "blocking", 0)
                    if blocking is not None and self._is_false(blocking):
                        nonblocking = True
                    bound = self._arg(call, "timeout", 1)
                elif tag == "queue" and f.attr == "get":
                    kind, label = "queue", "queue.get"
                    blk = self._arg(call, "block", 0)
                    if blk is not None and self._is_false(blk):
                        nonblocking = True
                    bound = self._arg(call, "timeout", 1)
                elif tag == "queue" and f.attr == "put":
                    kind, label = "queue", "queue.put"
                    blk = self._arg(call, "block", 1)
                    if blk is not None and self._is_false(blk):
                        nonblocking = True
                    bound = self._arg(call, "timeout", 2)
                elif tag == "thread" and f.attr == "join":
                    kind, label = "thread", "Thread.join"
                    bound = self._arg(call, "timeout", 0)
                elif tag == "condition" and f.attr in ("wait", "wait_for"):
                    kind, label = "condition", "Condition.%s" % f.attr
                    bound = self._arg(call, "timeout",
                                      0 if f.attr == "wait" else 1)
                elif tag == "event" and f.attr == "wait":
                    kind, label = "event", "Event.wait"
                    bound = self._arg(call, "timeout", 0)
                elif tag == "popen" and f.attr in ("wait", "communicate"):
                    kind, label = "popen", "Popen.%s" % f.attr
                    bound = self._arg(call, "timeout",
                                      0 if f.attr == "wait" else 1)
                elif self.is_thread_class and f.attr == "join" \
                        and isinstance(base, ast.Name) \
                        and base.id == "self":
                    kind, label = "thread", "Thread.join"
                    bound = self._arg(call, "timeout", 0)
        if kind is None or nonblocking:
            return
        bounded = bound is not None and self._bounded(bound)
        line = call.lineno
        ann = (blocking_annotation(self.src.lines[line - 1])
               if line <= len(self.src.lines) else None)
        if ann is None and line >= 2:
            ann = blocking_annotation(self.src.lines[line - 2])
        self.sites.append(_Site(line, kind, label, bounded, held,
                                ann is not None))

    # -- call edges -------------------------------------------------------

    def _edges(self, call: ast.Call) -> None:
        recv_types = None
        f = call.func
        if isinstance(f, ast.Attribute):
            attr = _self_attr(f.value)
            if attr is not None and self.cls is not None:
                t = self.cls.attr_types.get(attr)
                if t is not None:
                    recv_types = {t}
        for info, _ctor, _cls in self.an.graph.resolve(
                call, self.fi, recv_types=recv_types):
            if info is not None and ".<nested>." not in info.qname:
                self.callees.add(info.qname)

    # -- statement walk ---------------------------------------------------

    def _scan_expr(self, node, held: frozenset) -> None:
        """Catalog + edges over every call in an expression/leaf stmt."""
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._match(sub, held)
                self._edges(sub)

    def _walk(self, stmts, held: frozenset) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure: its body runs later, but its call sites
                # belong to this function's request path (it is handed
                # to call_with_retries / an executor and invoked on
                # behalf of this request).  Fresh locals, no held locks.
                saved = (self.env, self.local_types, self.sock_timeout)
                self.env, self.local_types = dict(self.env), dict(
                    self.local_types)
                self.sock_timeout = set(self.sock_timeout)
                self._walk(st.body, frozenset())
                self.env, self.local_types, self.sock_timeout = saved
                continue
            if isinstance(st, ast.ClassDef):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in st.items:
                    self._scan_expr(item.context_expr, held)
                    attr = _self_attr(item.context_expr)
                    if attr is not None and self.cls is not None \
                            and attr in self.cls.locks:
                        acquired.add(attr)
                self._walk(st.body, held | frozenset(acquired))
                continue
            if isinstance(st, ast.If):
                self._scan_expr(st.test, held)
                before = dict(self.env)
                self._walk(st.body, held)
                after_body = self.env
                self.env = dict(before)
                self._walk(st.orelse, held)
                # optimistic join: one bounding path sanctions the name
                for name, ok in after_body.items():
                    if ok:
                        self.env[name] = True
                continue
            if isinstance(st, (ast.While, ast.For)):
                self._scan_expr(getattr(st, "test", None), held)
                self._scan_expr(getattr(st, "iter", None), held)
                self._walk(st.body, held)
                self._walk(st.orelse, held)
                continue
            if isinstance(st, ast.Try):
                self._walk(st.body, held)
                for h in st.handlers:
                    self._walk(h.body, held)
                self._walk(st.orelse, held)
                self._walk(st.finalbody, held)
                continue
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                self._scan_expr(st.value, held)
                name = st.targets[0].id
                tag = self._ctor_tag(st.value)
                if tag is not None:
                    self.local_types[name] = tag
                self.env[name] = self._bounded(st.value)
                continue
            self._scan_expr(st, held)


class _Analysis:
    """The shared whole-program pass both analyzers read."""

    def __init__(self, ctx: LintContext):
        bucket = ctx.bucket("blocking")
        self.graph = get_callgraph(ctx)
        self.dirs = tuple(bucket.get("paths", BLOCKING_DIRS))
        self.entry_methods = frozenset(
            bucket.get("entry_methods", ENTRY_METHODS))
        self.entry_prefixes = tuple(
            bucket.get("entry_prefixes", ENTRY_PREFIXES))
        self.entry_qnames = frozenset(
            bucket.get("entry_qnames", ENTRY_QNAMES))
        self.module_consts: dict[str, dict[str, bool]] = {}
        self.attr_bounds: dict[tuple[str, str], dict[str, bool]] = {}
        self.classes: dict[tuple[str, str], ClassAnnotations] = {}
        self.scans: dict[str, _FnScan] = {}
        self.fn_summary: dict[str, bool] = {}   # qname -> returns bounded
        self._summarizing: set[str] = set()

    # -- scope ------------------------------------------------------------

    def in_scope(self, path: str) -> bool:
        return path.startswith(self.dirs) or \
            any(d in path for d in self.dirs)

    # -- bound evaluation (the taint-sanitizer mirror) --------------------

    def eval_bound(self, expr, env: dict, cls: ClassAnnotations | None,
                   fi) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, (int, float)) \
                and not isinstance(expr.value, bool)
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            return self.module_consts.get(fi.module, {}).get(
                expr.id, False)
        if isinstance(expr, ast.Attribute):
            attr = _self_attr(expr)
            if attr is not None and cls is not None:
                return self.attr_bounds.get(
                    (cls.path, cls.name), {}).get(attr, False)
            return False
        if isinstance(expr, ast.BinOp):
            return self.eval_bound(expr.left, env, cls, fi) and \
                self.eval_bound(expr.right, env, cls, fi)
        if isinstance(expr, ast.UnaryOp):
            return self.eval_bound(expr.operand, env, cls, fi)
        if isinstance(expr, ast.IfExp):
            return self.eval_bound(expr.body, env, cls, fi) or \
                self.eval_bound(expr.orelse, env, cls, fi)
        if isinstance(expr, ast.Call):
            return self._eval_call(expr, env, cls, fi)
        return False

    def _eval_call(self, call: ast.Call, env, cls, fi) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            # deadline.remaining_ms() and kin: THE sanctioned source
            if f.attr in _DEADLINE_METHODS:
                return True
            if f.attr in _CONFIG_GETTERS and call.args:
                key = call.args[0]
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str) \
                        and _TIMEOUT_KEY.search(key.value):
                    return True
        if isinstance(f, ast.Name):
            # min(): a clamp — ANY bounded arm launders the whole
            # expression (mirrors taint's sanitizer recognition);
            # max()/sum(): bounded only when every arm is
            if f.id == "min" and call.args:
                return any(self.eval_bound(a, env, cls, fi)
                           for a in call.args)
            if f.id in ("max", "sum") and call.args:
                return all(self.eval_bound(a, env, cls, fi)
                           for a in call.args)
            if f.id in ("int", "float", "abs", "round") and call.args:
                return self.eval_bound(call.args[0], env, cls, fi)
        # a repo function whose every return is bounded (one-level
        # summary with a cycle guard; e.g. a `_request_timeout_s()`
        # helper that clamps a config attr to the deadline remainder)
        for info, is_ctor, _cls in self.graph.resolve(call, fi):
            if info is not None and not is_ctor \
                    and self._returns_bounded(info):
                return True
        return False

    def _returns_bounded(self, fi) -> bool:
        q = fi.qname
        if q in self.fn_summary:
            return self.fn_summary[q]
        if q in self._summarizing:
            return False
        self._summarizing.add(q)
        try:
            cls = self.classes.get((fi.path, fi.klass)) if fi.klass \
                else None
            # linear optimistic pre-pass over the function's own
            # single-name assignments, then every return must be bounded
            env: dict[str, bool] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    if self.eval_bound(node.value, env, cls, fi):
                        env[name] = True
            returns = [n for n in ast.walk(fi.node)
                       if isinstance(n, ast.Return) and n.value is not None]
            ok = bool(returns) and all(
                self.eval_bound(r.value, env, cls, fi) for r in returns)
        finally:
            self._summarizing.discard(q)
        self.fn_summary[q] = ok
        return ok

    # -- the pass ---------------------------------------------------------

    def run(self, ctx: LintContext) -> None:
        in_scope = [s for s in ctx.files if self.in_scope(s.path)]
        # module constants, class annotations, and Thread subclasses all
        # come from the shared per-run index (built once, used by every
        # interprocedural analyzer); attribute bound provenance stays
        # local — it is deadline-specific
        index = get_ast_index(ctx)
        self.module_consts = index.module_consts
        self.classes = index.classes
        thread_classes = index.thread_classes
        for src in in_scope:
            mod = self.graph.modules.get(module_name(src.path))
            if mod is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    self._attr_bound_pass(src, node, mod)
        # per-function scans
        for src in in_scope:
            mod = self.graph.modules.get(module_name(src.path))
            if mod is None:
                continue
            fns = list(mod.functions.values())
            for cname, methods in mod.classes.items():
                fns.extend(methods.values())
            for fi in fns:
                cls = self.classes.get((src.path, fi.klass)) \
                    if fi.klass else None
                scan = _FnScan(fi, src, self, cls,
                               (src.path, fi.klass) in thread_classes)
                scan.run()
                self.scans[fi.qname] = scan

    def _attr_bound_pass(self, src: SourceFile, node: ast.ClassDef,
                         mod) -> None:
        info = self.classes[(src.path, node.name)]
        bounds = self.attr_bounds.setdefault((src.path, node.name), {})
        any_fi = next(iter(mod.classes.get(node.name, {}).values()), None)
        if any_fi is None:
            return
        for _round in (0, 1):
            for m in node.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                env: dict[str, bool] = {}
                for sub in ast.walk(m):
                    if not (isinstance(sub, ast.Assign)
                            and len(sub.targets) == 1):
                        continue
                    tgt, val = sub.targets[0], sub.value
                    if isinstance(tgt, ast.Name):
                        if self.eval_bound(val, env, info, any_fi):
                            env[tgt.id] = True
                        continue
                    attr = _self_attr(tgt)
                    if attr is not None and self.eval_bound(
                            val, env, info, any_fi):
                        bounds[attr] = True

    # -- reachability -----------------------------------------------------

    def is_entry(self, qname: str, name: str) -> bool:
        return qname in self.entry_qnames or name in self.entry_methods \
            or qname.startswith(self.entry_prefixes)

    def request_paths(self) -> dict[str, str]:
        """qname -> the entry point it is reachable from (BFS, sorted
        for deterministic attribution)."""
        via: dict[str, str] = {}
        queue: list[str] = []
        for q in sorted(self.scans):
            fi = self.scans[q].fi
            if self.is_entry(q, fi.name):
                via[q] = q
                queue.append(q)
        while queue:
            q = queue.pop(0)
            for callee in sorted(self.scans[q].callees):
                if callee in self.scans and callee not in via:
                    via[callee] = via[q]
                    queue.append(callee)
        return via


def _analysis(ctx: LintContext) -> dict:
    bucket = ctx.bucket("blocking")
    if "deadline_findings" in bucket:
        return bucket
    an = _Analysis(ctx)
    an.run(ctx)
    via = an.request_paths()
    deadline: list[Finding] = []
    hold: list[Finding] = []
    request_sites: set[tuple[str, str]] = set()
    seen: set[tuple] = set()
    for qname in sorted(via):
        scan = an.scans[qname]
        fi = scan.fi
        request_sites.add((fi.path, fi.name))
        entry_fi = an.scans[via[qname]].fi
        entry = entry_fi.name
        entry_rel = ((entry_fi.path, entry_fi.node.lineno,
                      "request-serving entry '%s'" % entry_fi.qname),)
        cls = an.classes.get((fi.path, fi.klass)) if fi.klass else None
        relevant = frozenset(cls.guarded.values()) if cls else frozenset()
        for site in scan.sites:
            if site.annotated:
                continue
            key = (fi.path, site.line, site.kind, site.label)
            if key in seen:
                continue
            seen.add(key)
            if site.kind == "sleep":
                deadline.append(Finding(
                    fi.path, site.line, RULE_SLEEP,
                    "time.sleep in '%s' is on a request-serving path "
                    "(reachable from '%s') — %s"
                    % (fi.name, entry, _SLEEP_HINT),
                    related=entry_rel))
            elif not site.bounded:
                deadline.append(Finding(
                    fi.path, site.line, RULE_UNBOUNDED,
                    "%s in '%s' on a request-serving path (reachable "
                    "from '%s') %s"
                    % (site.label, fi.name, entry, _UNBOUNDED_HINT),
                    related=entry_rel))
            if site.kind != "condition" and (site.held & relevant):
                lock = sorted(site.held & relevant)[0]
                hold.append(Finding(
                    fi.path, site.line, RULE_HOLD,
                    "%s in '%s' runs while holding lock '%s' on a "
                    "request-serving path (reachable from '%s') — a "
                    "stalled peer wedges every request contending this "
                    "lock; move the call outside the critical section "
                    "or use a per-resource lock"
                    % (site.label, fi.name, lock, entry),
                    related=entry_rel))
    bucket["deadline_findings"] = deadline
    bucket["hold_findings"] = hold
    bucket["request_sites"] = request_sites
    return bucket


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    return []


def finish_deadline(ctx: LintContext) -> list[Finding]:
    return list(_analysis(ctx)["deadline_findings"])


def finish_hold(ctx: LintContext) -> list[Finding]:
    return list(_analysis(ctx)["hold_findings"])


def static_request_paths(root: str | None = None,
                         paths: tuple[str, ...] = ("opentsdb_tpu",)
                         ) -> set[tuple[str, str]]:
    """(repo-relative path, function name) pairs on request-serving
    paths — the static set tsdbsan's blocked-past-deadline watcher
    cross-references its runtime observations against
    (tools/sanitize/deadlock.py), mirroring static_order_edges."""
    from tools.lint.core import REPO_ROOT, run_lint
    ctx = LintContext(root or REPO_ROOT)
    run_lint(paths, root=root or REPO_ROOT,
             analyzers=[DEADLINE_ANALYZER], ctx=ctx)
    return set(ctx.bucket("blocking").get("request_sites", set()))


DEADLINE_ANALYZER = Analyzer(
    "deadline_discipline", (RULE_UNBOUNDED, RULE_SLEEP),
    check, finish_deadline)
HOLD_LOCK_ANALYZER = Analyzer(
    "hold_lock_while_blocking", (RULE_HOLD,), check, finish_hold)
