"""Cache-coherence & stale-state analysis: every cached artifact's
read-set mutation must reach its registered invalidator.

PR 6's review pass fixed three independent instances of one bug class —
global state mutated without dropping the caches derived from it
(`set_hysteresis` not clearing the jit mode caches, `reload_calibration`
callers having to "remember the second half", `OnlineCalibrator`
leaking process-global installs past shutdown).  Stale caches in this
codebase produce *wrong answers*, not slow ones: a compiled program
bakes mode policy in at trace time and keeps serving the old policy
forever.  This analyzer makes the invalidation discipline a checked
contract.

Model (three registries, one rule):

  cached artifacts
      * `functools.lru_cache` / `functools.cache` callables — the
        registered invalidator is `<fn>.cache_clear()`.
      * module-scope `X = jax.jit(fn, ...)` bindings (the jit mode
        caches in ops/) — the registered invalidator is
        `X.clear_cache()`; the cache READS whatever `fn` traces.
      * manual dict/attr caches declared with
        `# cache: <name> invalidated-by: <func>`
        (grammar in tools/lint/annotations.py).  Several declarations
        may share one cache name — a cache can have more than one
        backing global (table + bookkeeping set).  `invalidated-by:
        none` declares the read-set immutable; the analyzer verifies
        that no mutable state can reach it.

  read-set
      For each cached artifact, the transitive set of mutable module
      globals its reader functions consult (callgraph closure over
      bare-name / self / module-alias calls; attribute-devirtualized
      calls are deliberately excluded so read-sets stay tight).  A
      read of ANOTHER cache's backing global imports that cache's
      read-set instead (read-through): the jit pipelines read the
      cost-table cache `_COSTS`, so a mutation of `_LIVE` obligates
      BOTH `reload_calibration` (the table's invalidator) and the jit
      `clear_cache` set — which `reload_calibration` reaches
      transitively.  Mutable = assigned under a `global` declaration,
      or mutated in place (`.clear()/.update()/[k] = ...`) on a module
      global, anywhere in a function body.

  the coherence rule
      Every mutation site of a name in some cache's read-set must
      reach that cache's registered invalidator on the same
      non-exceptional path (statement walk in the resource_leak style:
      a `return` that crosses an undischarged obligation reports, and
      so does falling off the end).  Invalidators are recognized
      TRANSITIVELY through single entry points: `set_scan_mode` is
      coherent because it calls `_clear_dependent_caches`, and
      `install_live_calibration` because it calls
      `reload_calibration` — so deleting the cache-drop inside the
      entry point fails every mutation site routed through it.
      Exemptions: `__init__` bodies (pre-publication construction),
      the cache's own backing globals (fills/drops are the
      invalidator's business, checked by the gutted rule below), and
      mutations inside a function that IS the cache's registered
      invalidator.

  paired installs
      `# global-install[: <uninstaller>] paired-with: <func>` marks a
      process-global install site (live calibration layers, logging
      handlers, compile-log subscriptions, patched factories).  The
      pairing function must exist (same class, then module), must call
      the named uninstaller, and must be reachable from a
      shutdown/close/stop/__exit__ path.

Rules:

  cache-stale-mutation           a read-set mutation can finish (or
                                 early-return) without reaching the
                                 cache's invalidator
  cache-invalidator-gutted       a registered invalidator no longer
                                 drops any backing store of its cache
  cache-undeclared               a module-global dict used in the
                                 memo idiom (get-then-fill) with no
                                 `# cache:` declaration and no
                                 lru_cache
  cache-bad-annotation           a `# cache:` annotation that names no
                                 resolvable declaration/invalidator,
                                 or conflicting invalidators for one
                                 cache name
  install-missing-uninstall      pairing function absent, or it never
                                 calls the declared uninstaller
  install-unreachable-uninstall  pairing function exists but no
                                 shutdown/close/stop/__exit__ path
                                 reaches it
"""

from __future__ import annotations

import ast
import dataclasses

from tools.lint.annotations import cache_annotation, install_annotation
from tools.lint.callgraph import FuncInfo, get_callgraph
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_STALE = "cache-stale-mutation"
RULE_GUTTED = "cache-invalidator-gutted"
RULE_UNDECLARED = "cache-undeclared"
RULE_BAD_ANN = "cache-bad-annotation"
RULE_INSTALL_MISSING = "install-missing-uninstall"
RULE_INSTALL_UNREACHABLE = "install-unreachable-uninstall"

# receiver-method calls that mutate a module-global container in place
MUTATORS = frozenset({"clear", "update", "setdefault", "pop", "append",
                      "extend", "add", "remove", "discard", "insert",
                      "popitem"})
# tokens that clear a compiled-program / lru cache
CLEAR_METHODS = frozenset({"clear_cache", "cache_clear"})
# function names that anchor a shutdown/teardown path
SHUTDOWN_NAMES = frozenset({"shutdown", "close", "stop", "__exit__",
                            "__del__", "uninstall", "teardown"})
_LRU_NAMES = frozenset({"lru_cache", "cache"})

_FIXPOINT_MAX = 40


@dataclasses.dataclass
class CacheArtifact:
    name: str                      # display name (qname or annotation)
    kind: str                      # 'lru' | 'jit' | 'manual'
    module: str
    path: str
    line: int
    backing: set                   # {(module, global)} — empty for attr
    attr_backing: set              # {(class, attr)} for self.X caches
    readers: list                  # [FuncInfo]
    invalidator: str | None        # annotated func name, or None
    # (module, binding-name) tokens whose .clear_cache()/.cache_clear()
    # invalidates this cache (lru/jit kinds)
    tokens: set = dataclasses.field(default_factory=set)
    read_set: set = dataclasses.field(default_factory=set)
    # `invalidated-by: none` — read-set declared immutable; verified
    declared_none: bool = False
    # resolved FuncInfo of the registered invalidator, set in finish()
    invalidator_info: object = None


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    # everything is whole-program: see finish()
    del src, ctx
    return []


# --------------------------------------------------------------------- #
# Per-module fact extraction                                            #
# --------------------------------------------------------------------- #

def _module_globals(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)) and \
                isinstance(st.target, ast.Name):
            out.add(st.target.id)
    return out


def _decl_on_line(tree: ast.Module, lineno: int) -> tuple[str, int] | None:
    """The module-scope global declared on `lineno` or the next
    declaration after it (standalone annotation comment above)."""
    best = None
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            name, ln = st.targets[0].id, st.lineno
        elif isinstance(st, ast.AnnAssign) and \
                isinstance(st.target, ast.Name):
            name, ln = st.target.id, st.lineno
        else:
            continue
        if st.lineno <= lineno <= (st.end_lineno or st.lineno):
            return name, ln
        if st.lineno > lineno and (best is None or st.lineno < best[1]):
            best = (name, st.lineno)
    # a standalone comment annotates the declaration directly below it
    if best is not None and best[1] <= lineno + 2:
        return best
    return None


def _attr_decl_on_line(tree: ast.Module, lineno: int
                       ) -> tuple[str, str] | None:
    """(class, attr) when `lineno` declares a self.<attr> = ... inside a
    class body (attr-cache annotation).  Like `_decl_on_line`, a
    standalone comment annotates the declaration directly below it."""
    best = None
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    if node.lineno <= lineno <= (node.end_lineno or
                                                 node.lineno):
                        return cls.name, t.attr
                    if node.lineno > lineno and (
                            best is None or node.lineno < best[2]):
                        best = (cls.name, t.attr, node.lineno)
    if best is not None and best[2] <= lineno + 2:
        return best[0], best[1]
    return None


def _global_decls(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _lru_decorated(node) -> bool:
    for dec in node.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(d, ast.Name) and d.id in _LRU_NAMES:
            return True
        if isinstance(d, ast.Attribute) and d.attr in _LRU_NAMES:
            return True
    return False


class _Facts:
    """Everything finish() needs, computed once per LintContext."""

    def __init__(self, ctx: LintContext):
        self.cg = get_callgraph(ctx)
        self.files = {src.path: src for src in ctx.files}
        self.mod_globals: dict[str, set[str]] = {}
        self.mod_src: dict[str, SourceFile] = {}
        for src in ctx.files:
            from tools.lint.callgraph import module_name
            mod = module_name(src.path)
            self.mod_globals[mod] = _module_globals(src.tree)
            self.mod_src[mod] = src
        # (module, name) -> [(FuncInfo, stmt, line)]
        self.mutations: dict[tuple, list] = {}
        # funcqname -> {(module, name)} direct global reads
        self.reads: dict[str, set] = {}
        # funcqname -> [FuncInfo] resolved callees (restricted forms)
        self.callees: dict[str, list] = {}
        # funcqname -> {(module, binding)} cleared via token methods
        self.clear_tokens: dict[str, set] = {}
        # funcqname -> {(module, global)} dropped (None/clear/del)
        self.drops: dict[str, set] = {}
        # funcqname -> {(class, attr)} attr stores dropped
        self.attr_drops: dict[str, set] = {}
        for fi in self.cg.funcs.values():
            self._summarize(fi)

    # -- helpers ---------------------------------------------------------

    def _target_module(self, caller: FuncInfo, alias: str) -> str | None:
        mod = self.cg.modules.get(caller.module)
        if mod is None:
            return None
        tgt = mod.imports.get(alias)
        return tgt if tgt in self.cg.modules else None

    def _global_ref(self, caller: FuncInfo, node: ast.expr
                    ) -> tuple | None:
        """(module, name) when `node` names a module global: a bare
        Name of the caller's module, or alias.NAME of an imported
        module."""
        if isinstance(node, ast.Name):
            if node.id in self.mod_globals.get(caller.module, ()):
                return (caller.module, node.id)
            return None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            tgt = self._target_module(caller, node.value.id)
            if tgt and node.attr in self.mod_globals.get(tgt, ()):
                return (tgt, node.attr)
        return None

    def _binding_ref(self, caller: FuncInfo, node: ast.expr
                     ) -> tuple | None:
        """(module, binding) for a clear receiver: a bare Name in the
        caller's module, or alias.NAME of an imported module."""
        if isinstance(node, ast.Name):
            return (caller.module, node.id)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            tgt = self._target_module(caller, node.value.id)
            if tgt:
                return (tgt, node.attr)
        return None

    def clear_refs(self, fi: FuncInfo, root: ast.AST) -> set:
        """Every (module, binding) whose compiled/lru cache is cleared
        under `root`: direct `X.clear_cache()` / `X.cache_clear()`
        receivers plus each binding listed in the clear-loop idiom
        `for fn in (a, mod.b, ...): fn.clear_cache()`.  The ONE
        definition of clear recognition — the summary pass
        (_summarize) and the obligation walk (_ObligationWalk) both
        consume it, so they cannot drift."""
        out: set = set()
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in CLEAR_METHODS:
                ref = self._binding_ref(fi, node.func.value)
                if ref is not None:
                    out.add(ref)
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name) and \
                    isinstance(node.iter, (ast.Tuple, ast.List)):
                loopvar = node.target.id
                clears = any(
                    isinstance(sub, ast.Call) and
                    isinstance(sub.func, ast.Attribute) and
                    sub.func.attr in CLEAR_METHODS and
                    isinstance(sub.func.value, ast.Name) and
                    sub.func.value.id == loopvar
                    for st in node.body for sub in ast.walk(st))
                if not clears:
                    continue
                for el in node.iter.elts:
                    ref = self._binding_ref(fi, el)
                    if ref is not None:
                        out.add(ref)
        return out

    def _summarize(self, fi: FuncInfo) -> None:
        reads: set = set()
        callees: list = []
        tokens: set = set()
        drops: set = set()
        attr_drops: set = set()
        gdecls = _global_decls(fi.node)
        local_assigned = {
            t.id for st in ast.walk(fi.node)
            if isinstance(st, ast.Assign)
            for t in st.targets if isinstance(t, ast.Name)}
        params = set(fi.params)

        def is_global_name(name: str) -> bool:
            if name not in self.mod_globals.get(fi.module, ()):
                return False
            if name in gdecls:
                return True
            return name not in local_assigned and name not in params

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    is_global_name(node.id):
                reads.add((fi.module, node.id))
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Name):
                ref = self._global_ref(fi, node)
                if ref is not None:
                    reads.add(ref)
            elif isinstance(node, ast.Call):
                self._call_facts(fi, node, callees)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                self._assign_facts(fi, node, gdecls, drops, attr_drops)
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    base = tgt.value if isinstance(tgt, ast.Subscript) \
                        else tgt
                    if isinstance(base, ast.Name) and \
                            is_global_name(base.id):
                        ref = (fi.module, base.id)
                        drops.add(ref)
                        self._note_mutation(fi, ref, node)
        # direct clear calls + the clear-loop idiom, via the shared
        # recognizer the obligation walk also uses
        tokens |= self.clear_refs(fi, fi.node)
        # in-place container mutations + token loops
        self._mutation_facts(fi, gdecls, local_assigned, params)
        self.reads[fi.qname] = reads
        self.callees[fi.qname] = callees
        self.clear_tokens[fi.qname] = tokens
        # merge: _mutation_facts records `.clear()`-style drops directly
        self.drops.setdefault(fi.qname, set()).update(drops)
        self.attr_drops[fi.qname] = attr_drops

    def _call_facts(self, fi: FuncInfo, node: ast.Call,
                    callees: list) -> None:
        f = node.func
        # X.clear_cache() / X.cache_clear(): token collected by
        # clear_refs; never a callee to resolve
        if isinstance(f, ast.Attribute) and f.attr in CLEAR_METHODS:
            return
        # restricted resolution: bare names, self.m, alias.attr only —
        # unknown-receiver devirtualization would bloat read-sets with
        # every same-named method in the tree
        resolvable = isinstance(f, ast.Name)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            resolvable = (f.value.id == "self"
                          or self._target_module(fi, f.value.id)
                          is not None)
        if resolvable:
            for info, _ctor, _cls in self.cg.resolve(node, fi):
                if info is not None:
                    callees.append(info)

    def _assign_facts(self, fi: FuncInfo, node, gdecls: set,
                      drops: set, attr_drops: set) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id in gdecls:
                ref = (fi.module, t.id)
                self._note_mutation(fi, ref, node)
                if isinstance(node, ast.Assign) and \
                        _is_empty_value(node.value):
                    drops.add(ref)
            elif isinstance(t, ast.Subscript):
                base = t.value
                # subscript store into a module-global container
                if isinstance(base, ast.Name) and \
                        self._is_module_global_here(fi, base.id):
                    self._note_mutation(fi, (fi.module, base.id), node)
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and fi.klass is not None:
                if isinstance(node, ast.Assign) and \
                        _is_empty_value(node.value):
                    attr_drops.add((fi.klass, t.attr))

    def _is_module_global_here(self, fi: FuncInfo, name: str) -> bool:
        if name not in self.mod_globals.get(fi.module, ()):
            return False
        params = set(fi.params)
        if name in params:
            return False
        for st in ast.walk(fi.node):
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    if isinstance(t, ast.Name) and t.id == name and \
                            name not in _global_decls(fi.node):
                        return False
        return True

    def _mutation_facts(self, fi: FuncInfo, gdecls: set,
                        local_assigned: set, params: set) -> None:
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in MUTATORS):
                continue
            base = node.func.value
            if isinstance(base, ast.Name) and \
                    base.id in self.mod_globals.get(fi.module, ()) and \
                    base.id not in params and \
                    (base.id in gdecls or base.id not in local_assigned):
                ref = (fi.module, base.id)
                self._note_mutation(fi, ref, node)
                if node.func.attr in ("clear", "popitem"):
                    self.drops.setdefault(fi.qname, set()).add(ref)

    def _note_mutation(self, fi: FuncInfo, ref: tuple, node) -> None:
        self.mutations.setdefault(ref, []).append((fi, node))


def _is_empty_value(v: ast.expr) -> bool:
    """None / {} / [] / set() / dict() — a drop, not a fill."""
    if isinstance(v, ast.Constant) and v.value is None:
        return True
    if isinstance(v, (ast.Dict, ast.List, ast.Set)) and not getattr(
            v, "keys", getattr(v, "elts", None)):
        return True
    if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) and \
            v.func.id in ("dict", "set", "list") and not v.args:
        return True
    return False


# --------------------------------------------------------------------- #
# Registry construction                                                 #
# --------------------------------------------------------------------- #

def _build_registry(facts: _Facts, findings: list[Finding]
                    ) -> list[CacheArtifact]:
    caches: list[CacheArtifact] = []
    by_name: dict[tuple, CacheArtifact] = {}     # (module, ann-name)
    for path, src in sorted(facts.files.items()):
        from tools.lint.callgraph import module_name
        mod = module_name(path)
        # 1. annotated manual caches
        for i, line in enumerate(src.lines, start=1):
            ann = cache_annotation(line)
            if ann is None:
                continue
            cname, invalidator = ann
            decl = _decl_on_line(src.tree, i)
            attr = None if decl else _attr_decl_on_line(src.tree, i)
            if decl is None and attr is None:
                findings.append(Finding(
                    path, i, RULE_BAD_ANN,
                    "cache annotation %r matches no module-global or "
                    "self-attribute declaration" % cname))
                continue
            key = (mod, cname)
            art = by_name.get(key)
            if art is None:
                art = CacheArtifact(cname, "manual", mod, path, i,
                                    set(), set(), [],
                                    None if invalidator == "none"
                                    else invalidator,
                                    declared_none=invalidator == "none")
                by_name[key] = art
                caches.append(art)
            elif (invalidator == "none") != art.declared_none or (
                    invalidator != "none" and
                    art.invalidator != invalidator):
                findings.append(Finding(
                    path, i, RULE_BAD_ANN,
                    "cache %r declares conflicting invalidators"
                    % cname))
            if decl is not None:
                art.backing.add((mod, decl[0]))
            else:
                art.attr_backing.add(attr)
        # 2. lru_cache functions + module-scope jax.jit bindings
        for fi in facts.cg.funcs.values():
            if fi.path != path:
                continue
            if _lru_decorated(fi.node):
                art = CacheArtifact(fi.qname, "lru", mod, path,
                                    fi.node.lineno, {(mod, fi.name)},
                                    set(), [fi], None,
                                    tokens={(mod, fi.name)})
                caches.append(art)
        for st in src.tree.body:
            if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and isinstance(st.value, ast.Call)):
                continue
            f = st.value.func
            is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit"
                      and isinstance(f.value, ast.Name)
                      and f.value.id == "jax") or \
                     (isinstance(f, ast.Name) and f.id == "jit")
            if not is_jit or not st.value.args:
                continue
            binding = st.targets[0].id
            reader = None
            arg0 = st.value.args[0]
            if isinstance(arg0, ast.Name):
                reader = facts.cg.modules[mod].functions.get(arg0.id)
            art = CacheArtifact("%s.%s" % (mod, binding), "jit", mod,
                                path, st.lineno, {(mod, binding)},
                                set(), [reader] if reader else [],
                                None, tokens={(mod, binding)})
            caches.append(art)
    # readers of manual caches: any function with a genuine READ of a
    # backing global.  A drop-only touch (`X.clear()`, `X.pop()`) does
    # NOT make a function a reader — otherwise every invalidator would
    # import its cache's read-set and read-through would manufacture
    # false dependency cycles through the invalidation entry points.
    for art in caches:
        if art.kind != "manual":
            continue
        for fi in facts.cg.funcs.values():
            for mod, name in art.backing:
                if mod == fi.module and _reads_name(fi.node, name):
                    art.readers.append(fi)
                    break
    return caches


_DROP_METHODS = frozenset({"clear", "pop", "popitem"})


def _reads_name(fn, name: str) -> bool:
    """A Load of `name` that is not merely the receiver of a drop call."""
    loads = drops = 0
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and n.id == name and \
                isinstance(n.ctx, ast.Load):
            loads += 1
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in _DROP_METHODS and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == name:
            drops += 1
    return loads > drops


# --------------------------------------------------------------------- #
# Read-set closure + invalidator relation                               #
# --------------------------------------------------------------------- #

def _transitive_reads(facts: _Facts) -> dict[str, set]:
    summary = {q: set(r) for q, r in facts.reads.items()}
    for _ in range(_FIXPOINT_MAX):
        changed = False
        for q, callees in facts.callees.items():
            s = summary.setdefault(q, set())
            before = len(s)
            for c in callees:
                s |= summary.get(c.qname, set())
            changed |= len(s) != before
        if not changed:
            break
    return summary


def _resolve_invalidator(facts: _Facts, art: CacheArtifact
                         ) -> FuncInfo | None:
    name = art.invalidator
    if not name:
        return None
    mod = facts.cg.modules.get(art.module)
    if mod is None:
        return None
    head, _, tail = name.rpartition(".")
    if head:
        tgt = mod.imports.get(head, head)
        other = facts.cg.modules.get(tgt)
        if other is not None and tail in other.functions:
            return other.functions[tail]
        # Class.method in the same module
        fi = facts.cg.class_method(art.module, head, tail)
        if fi is not None:
            return fi
        return None
    if name in mod.functions:
        return mod.functions[name]
    # a method: any class in the module defining it
    for cls in mod.classes:
        fi = mod.classes[cls].get(name)
        if fi is not None:
            return fi
    tgt = mod.imports.get(name)
    if tgt:
        sym = facts.cg._symbol(tgt)
        if isinstance(sym, FuncInfo):
            return sym
    return None


def _drops_cache(facts: _Facts, art: CacheArtifact, start: FuncInfo,
                 depth: int = 4) -> bool:
    """True when `start` (transitively) drops one of the cache's
    backing stores or clears one of its tokens."""
    seen: set[str] = set()
    stack = [(start, 0)]
    while stack:
        fi, d = stack.pop()
        if fi.qname in seen or d > depth:
            continue
        seen.add(fi.qname)
        if facts.drops.get(fi.qname, set()) & art.backing:
            return True
        if facts.attr_drops.get(fi.qname, set()) & art.attr_backing:
            return True
        if facts.clear_tokens.get(fi.qname, set()) & art.tokens:
            return True
        for c in facts.callees.get(fi.qname, ()):
            stack.append((c, d + 1))
    return False


def _invalidator_funcs(facts: _Facts, caches: list[CacheArtifact],
                       findings: list[Finding]) -> dict[str, set]:
    """qname -> set of cache ids the function (transitively)
    invalidates.  Manual caches are single-entry-point: only the
    registered invalidator (and its transitive callers) count, and a
    registered invalidator that no longer drops its backing store is a
    `cache-invalidator-gutted` finding."""
    direct: dict[str, set] = {}
    for idx, art in enumerate(caches):
        if art.kind == "manual":
            if art.invalidator is None:     # invalidated-by: none
                continue
            inv = _resolve_invalidator(facts, art)
            if inv is None:
                findings.append(Finding(
                    art.path, art.line, RULE_BAD_ANN,
                    "cache %r names invalidator %r which resolves to "
                    "no scanned function" % (art.name, art.invalidator)))
                continue
            art.invalidator_info = inv
            if not _drops_cache(facts, art, inv):
                findings.append(Finding(
                    inv.path, inv.node.lineno, RULE_GUTTED,
                    "'%s' is the registered invalidator of cache %r "
                    "but no longer drops any of its backing stores "
                    "(%s)" % (inv.name, art.name,
                              ", ".join(sorted(n for _m, n
                                               in art.backing)) or
                              ", ".join(sorted("self.%s" % a
                                               for _c, a in
                                               art.attr_backing))))
                )
            direct.setdefault(inv.qname, set()).add(idx)
        else:
            for q, tokens in facts.clear_tokens.items():
                if tokens & art.tokens:
                    direct.setdefault(q, set()).add(idx)
    # transitive closure: F invalidates whatever its callees invalidate
    inval = {q: set(s) for q, s in direct.items()}
    for _ in range(_FIXPOINT_MAX):
        changed = False
        for q, callees in facts.callees.items():
            s = inval.setdefault(q, set())
            before = len(s)
            for c in callees:
                s |= inval.get(c.qname, set())
            changed |= len(s) != before
        if not changed:
            break
    return inval


# --------------------------------------------------------------------- #
# The path walk: mutation must reach invalidator                        #
# --------------------------------------------------------------------- #

class _ObligationWalk:
    """One function, one cache: walk the statement list tracking
    undischarged mutation obligations (resource_leak style).  A
    `return` crossing a pending obligation reports; raises are
    exceptional exits and out of scope.  Discharge is branch-aware
    for `if`: a clear inside one branch counts only when every branch
    clears (or exits exceptionally) — a conditionally-skipped
    invalidation is exactly the bug class.  Loop and try bodies stay
    optimistic (a clear anywhere inside counts), documented in
    docs/static_analysis.md."""

    def __init__(self, facts: _Facts, fi: FuncInfo, cache_idx: int,
                 inval: dict[str, set], mutation_nodes: list,
                 cache_name: str, path: str):
        self.facts = facts
        self.fi = fi
        self.idx = cache_idx
        self.inval = inval
        self.mutations = {id(n): n for n in mutation_nodes}
        self.cache_name = cache_name
        self.path = path
        self.pending: dict[int, object] = {}
        self.findings: list[Finding] = []

    def _discharges(self, st: ast.stmt) -> bool:
        # direct clears + the clear-loop idiom, via the same recognizer
        # _Facts._summarize feeds the invalidator summaries from
        if any(ref in self._tokens
               for ref in self.facts.clear_refs(self.fi, st)):
            return True
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in CLEAR_METHODS:
                continue    # handled by clear_refs above
            if isinstance(f, ast.Name) or (
                    isinstance(f, ast.Attribute) and
                    isinstance(f.value, ast.Name)):
                for info, _c, _n in self.facts.cg.resolve(node, self.fi):
                    if info is not None and self.idx in \
                            self.inval.get(info.qname, set()):
                        return True
        return False

    def _stmt_discharges(self, st: ast.stmt) -> bool:
        """Branch-aware discharge for one statement."""
        if isinstance(st, ast.If):
            return (self._branch_discharges(st.body) and bool(st.orelse)
                    and self._branch_discharges(st.orelse))
        if isinstance(st, ast.With):
            return self._branch_discharges(st.body)
        return self._discharges(st)

    def _branch_discharges(self, stmts) -> bool:
        for s in stmts:
            if isinstance(s, ast.Raise):
                return True       # exceptional exit — out of scope
            if self._stmt_discharges(s):
                return True
        return False

    def run(self, tokens: set) -> list[Finding]:
        self._tokens = tokens
        self._walk(self.fi.node.body, False)
        for mid, node in self.pending.items():
            self._report(node)
        return self.findings

    def _report(self, node) -> None:
        self.findings.append(Finding(
            self.path, node.lineno, RULE_STALE,
            "mutation in '%s' is in the read-set of cache %r but no "
            "non-exceptional path from it reaches the cache's "
            "invalidator — stale entries will keep serving the old "
            "state" % (self.fi.name, self.cache_name)))

    def _walk(self, stmts, protected: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if self._stmt_discharges(st):
                self.pending.clear()
            if isinstance(st, ast.Return):
                for mid, node in list(self.pending.items()):
                    if not protected:
                        self._report(node)
                    self.pending.pop(mid)
                continue
            if isinstance(st, ast.Raise):
                self.pending.clear()      # exceptional exit: out of scope
                continue
            if isinstance(st, ast.Try):
                fin_discharges = any(self._discharges(f)
                                     for f in st.finalbody)
                self._walk(st.body, protected or fin_discharges)
                for h in st.handlers:
                    self._walk(h.body, protected or fin_discharges)
                self._walk(st.orelse, protected or fin_discharges)
                self._walk(st.finalbody, protected)
                if fin_discharges:
                    self.pending.clear()
                continue
            if isinstance(st, (ast.If, ast.While, ast.For)):
                self._walk(st.body, protected)
                self._walk(st.orelse, protected)
            elif isinstance(st, ast.With):
                self._walk(st.body, protected)
            # activate obligations declared by THIS statement (after
            # discharge: `x = v` and the invalidating call never share
            # a statement in the idiom this checks)
            for node in ast.walk(st):
                if id(node) in self.mutations:
                    self.pending[id(node)] = node
                    self.mutations.pop(id(node), None)


# --------------------------------------------------------------------- #
# finish: the whole-program pass                                        #
# --------------------------------------------------------------------- #

def finish(ctx: LintContext) -> list[Finding]:
    if not ctx.files:
        return []
    findings: list[Finding] = []
    facts = _Facts(ctx)
    caches = _build_registry(facts, findings)
    summaries = _transitive_reads(facts)

    backing_of: dict[tuple, int] = {}
    for idx, art in enumerate(caches):
        for ref in art.backing:
            backing_of.setdefault(ref, idx)
    all_backing = set(backing_of)

    mutable = set(facts.mutations) - all_backing

    # raw read-sets, then read-through backing names of other caches
    for art in caches:
        rs: set = set()
        for fi in art.readers:
            rs |= summaries.get(fi.qname, set())
        art.read_set = rs
    for _ in range(_FIXPOINT_MAX):
        changed = False
        for idx, art in enumerate(caches):
            for ref in list(art.read_set & all_backing):
                other = backing_of[ref]
                if other != idx:
                    before = len(art.read_set)
                    art.read_set |= caches[other].read_set - all_backing
                    changed |= len(art.read_set) != before
        if not changed:
            break
    for art in caches:
        art.read_set = (art.read_set - all_backing) & mutable

    inval = _invalidator_funcs(facts, caches, findings)

    # the coherence rule
    for ref in sorted(mutable):
        interested = [i for i, a in enumerate(caches)
                      if ref in a.read_set]
        if not interested:
            continue
        for fi, node in facts.mutations[ref]:
            if fi.name == "__init__":
                continue        # pre-publication construction
            for i in interested:
                art = caches[i]
                if art.kind == "manual" and art.invalidator is None:
                    findings.append(Finding(
                        fi.path, node.lineno, RULE_STALE,
                        "mutation in '%s' reaches cache %r which is "
                        "declared `invalidated-by: none` (immutable "
                        "read-set) — declare a real invalidator or "
                        "remove the mutable dependency"
                        % (fi.name, art.name)))
                    continue
                if art.kind == "manual" and art.invalidator_info is fi:
                    continue    # the invalidator's own bookkeeping
                if self_invalidates(fi, i, inval):
                    walk = _ObligationWalk(
                        facts, fi, i, inval,
                        [node], art.name, fi.path)
                    findings.extend(walk.run(art.tokens))
                else:
                    findings.append(Finding(
                        fi.path, node.lineno, RULE_STALE,
                        "'%s' mutates state in the read-set of cache "
                        "%r but never reaches its invalidator%s"
                        % (fi.name, art.name,
                           " ('%s')" % art.invalidator
                           if art.invalidator else "")))

    findings.extend(_undeclared_memos(facts, caches))
    findings.extend(_check_installs(facts))
    return findings


def self_invalidates(fi: FuncInfo, idx: int,
                     inval: dict[str, set]) -> bool:
    return idx in inval.get(fi.qname, set())


# --------------------------------------------------------------------- #
# Undeclared memo caches                                                #
# --------------------------------------------------------------------- #

def _undeclared_memos(facts: _Facts,
                      caches: list[CacheArtifact]) -> list[Finding]:
    declared = set()
    for art in caches:
        declared |= art.backing
    out: list[Finding] = []
    for mod, src in sorted(facts.mod_src.items()):
        for st in src.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                name, value = st.targets[0].id, st.value
            elif isinstance(st, ast.AnnAssign) and \
                    isinstance(st.target, ast.Name) and \
                    st.value is not None:
                name, value = st.target.id, st.value
            else:
                continue
            if not (isinstance(value, ast.Dict) and not value.keys) and \
               not (isinstance(value, ast.Call) and
                    isinstance(value.func, ast.Name) and
                    value.func.id == "dict" and not value.args):
                continue
            if (mod, name) in declared:
                continue
            filled = read = False
            for fi in facts.cg.funcs.values():
                if fi.module != mod:
                    continue
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Subscript) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == name:
                                filled = True
                    elif isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "get" and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == name:
                        read = True
                    elif isinstance(node, ast.Compare) and \
                            any(isinstance(op, (ast.In, ast.NotIn))
                                for op in node.ops) and \
                            isinstance(node.comparators[-1], ast.Name) \
                            and node.comparators[-1].id == name:
                        read = True
            if filled and read:
                out.append(Finding(
                    src.path, st.lineno, RULE_UNDECLARED,
                    "module global %r is used as a memo cache "
                    "(get-then-fill) but declares no invalidator — "
                    "add `# cache: <name> invalidated-by: <func>` "
                    "(or `none` for an immutable read-set)" % name))
    return out


# --------------------------------------------------------------------- #
# Paired global installs                                                #
# --------------------------------------------------------------------- #

def _enclosing_func(facts: _Facts, path: str, line: int
                    ) -> FuncInfo | None:
    best = None
    for fi in facts.cg.funcs.values():
        if fi.path != path:
            continue
        if fi.node.lineno <= line <= (fi.node.end_lineno or 10 ** 9):
            if best is None or fi.node.lineno > best.node.lineno:
                best = fi
    return best


def _calls_name(facts: _Facts, start: FuncInfo, target: str,
                depth: int = 3) -> bool:
    """Does `start` (transitively, depth-bounded) contain a call whose
    terminal name is `target`?"""
    seen: set[str] = set()
    stack = [(start, 0)]
    while stack:
        fi, d = stack.pop()
        if fi.qname in seen or d > depth:
            continue
        seen.add(fi.qname)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else \
                f.attr if isinstance(f, ast.Attribute) else None
            if name == target:
                return True
        for c in facts.callees.get(fi.qname, ()):
            stack.append((c, d + 1))
    return False


def _shutdown_reachable(facts: _Facts) -> set[str]:
    """qnames reachable (as callees) from any shutdown-named function,
    plus the shutdown-named functions themselves."""
    out: set[str] = set()
    stack = [fi for fi in facts.cg.funcs.values()
             if fi.name in SHUTDOWN_NAMES]
    out |= {fi.qname for fi in stack}
    while stack:
        fi = stack.pop()
        for c in facts.callees.get(fi.qname, ()):
            if c.qname not in out:
                out.add(c.qname)
                stack.append(c)
    return out


def _check_installs(facts: _Facts) -> list[Finding]:
    out: list[Finding] = []
    reachable = None
    for path, src in sorted(facts.files.items()):
        for i, line in enumerate(src.lines, start=1):
            ann = install_annotation(line)
            if ann is None:
                continue
            uninstaller, paired = ann
            fi = _enclosing_func(facts, path, i)
            # resolve the pairing function: same class, then module
            target = None
            if fi is not None and fi.klass is not None:
                target = facts.cg.class_method(fi.module, fi.klass,
                                               paired.split(".")[-1])
            if target is None and fi is not None:
                mod = facts.cg.modules.get(fi.module)
                if mod is not None:
                    target = mod.functions.get(paired)
            if target is None:
                # any scanned class defining the method (cross-class
                # pairings: the installer and the owner differ)
                cands = facts.cg.methods_by_name.get(
                    paired.split(".")[-1], [])
                if len(cands) == 1:
                    target = cands[0]
            if target is None:
                out.append(Finding(
                    path, i, RULE_INSTALL_MISSING,
                    "global install pairs with %r which resolves to no "
                    "scanned function — the install has no uninstall"
                    % paired))
                continue
            if uninstaller is not None and not _calls_name(
                    facts, target, uninstaller.split(".")[-1]):
                out.append(Finding(
                    path, i, RULE_INSTALL_MISSING,
                    "pairing function '%s' never calls the declared "
                    "uninstaller '%s' — the global install leaks past "
                    "it" % (target.name, uninstaller)))
                continue
            if reachable is None:
                reachable = _shutdown_reachable(facts)
            if target.name not in SHUTDOWN_NAMES and \
                    target.qname not in reachable:
                out.append(Finding(
                    path, i, RULE_INSTALL_UNREACHABLE,
                    "pairing function '%s' is not reachable from any "
                    "shutdown/close/stop/__exit__ path — the uninstall "
                    "exists but nothing runs it" % target.name))
    return out


ANALYZER = Analyzer(
    "cache_coherence",
    (RULE_STALE, RULE_GUTTED, RULE_UNDECLARED, RULE_BAD_ANN,
     RULE_INSTALL_MISSING, RULE_INSTALL_UNREACHABLE),
    check, finish)
