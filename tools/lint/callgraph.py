"""Repo-wide call graph + per-function summaries for the v2 analyzers.

Everything is AST-derived — no imports are executed.  One CallGraph is
built per lint run (cached in the LintContext) and shared by the
shape/dtype, taint, and resource-leak analyzers.

Resolution strategy, in decreasing order of confidence:

  * bare name        -> nested def in the caller, module function,
                        `from x import f` symbol, or a class (constructor)
  * alias.attr       -> function/class of an imported module
  * self.m(...)      -> method m of the caller's class (then same-module
                        base classes by name)
  * obj.m(...)       -> name-based devirtualization: every class in the
                        scanned tree defining m, but only when at most
                        DEVIRT_MAX classes do — common names (`get`,
                        `close`, ...) resolve to nothing rather than to
                        everything.

Multi-target resolution returns *all* candidates; analyzers union the
effects, which over-approximates data flow but never invents call edges
to arbitrarily-named methods.
"""

from __future__ import annotations

import ast
import dataclasses

DEVIRT_MAX = 4

# Method names that collide with builtin str/list/dict/set methods are
# never name-devirtualized: `text.split(",")` must not resolve to every
# scanned class that happens to define split().  Receiver-TYPE-based
# resolution (resolve(..., recv_types=...)) still reaches these methods
# precisely.
BUILTIN_METHODS = frozenset({
    "split", "join", "strip", "lstrip", "rstrip", "get", "items", "keys",
    "values", "append", "extend", "pop", "update", "sort", "copy", "index",
    "count", "upper", "lower", "startswith", "endswith", "replace",
    "format", "encode", "decode", "find", "add", "remove", "discard",
    "insert", "clear", "setdefault", "read", "write", "readlines",
    "close", "open", "run", "send", "recv", "next", "flush", "reverse",
    "title", "search", "match", "group", "groups", "mark",
    # ndarray/jax-array reducers and casts: `keep.sum()` on a numpy
    # mask must not resolve to a scanned class's sum() method.
    "sum", "mean", "astype", "reshape", "tolist", "item",
})


@dataclasses.dataclass
class FuncInfo:
    qname: str                       # module[.Class].name
    module: str
    klass: str | None
    name: str
    node: ast.FunctionDef | ast.AsyncFunctionDef
    path: str                        # repo-relative posix path
    nested: dict = dataclasses.field(default_factory=dict)  # name -> FuncInfo

    @property
    def params(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    @property
    def is_method(self) -> bool:
        return self.klass is not None


@dataclasses.dataclass
class ModuleInfo:
    name: str
    path: str
    functions: dict = dataclasses.field(default_factory=dict)
    classes: dict = dataclasses.field(default_factory=dict)   # cls -> {meth: FuncInfo}
    bases: dict = dataclasses.field(default_factory=dict)     # cls -> [base names]
    imports: dict = dataclasses.field(default_factory=dict)   # alias -> dotted target


def module_name(relpath: str) -> str:
    parts = relpath.replace("\\", "/").split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p and p != "..")


class CallGraph:
    def __init__(self, files):
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.methods_by_name: dict[str, list[FuncInfo]] = {}
        self.classes_by_name: dict[str, list[tuple[str, str]]] = {}
        self.files = list(files)
        for src in self.files:
            self._index_module(src)

    # -- indexing --------------------------------------------------------

    def _index_module(self, src) -> None:
        mod = ModuleInfo(module_name(src.path), src.path)
        self.modules[mod.name] = mod
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes_by_name.setdefault(node.name, []).append(
                    (mod.name, node.name))
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(mod.name, node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = (
                        base + "." + a.name if base else a.name)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(mod, None, node, src.path)
            elif isinstance(node, ast.ClassDef):
                mod.bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)]
                methods = mod.classes.setdefault(node.name, {})
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fi = self._add_func(mod, node.name, sub, src.path)
                        methods[sub.name] = fi
                        self.methods_by_name.setdefault(sub.name,
                                                        []).append(fi)

    def _add_func(self, mod: ModuleInfo, klass: str | None, node,
                  path: str) -> FuncInfo:
        qname = ".".join(x for x in (mod.name, klass, node.name) if x)
        fi = FuncInfo(qname, mod.name, klass, node.name, node, path)
        self.funcs[qname] = fi
        if klass is None:
            mod.functions[node.name] = fi
        for sub in ast.walk(node):
            if sub is not node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested = FuncInfo(qname + ".<nested>." + sub.name, mod.name,
                                  klass, sub.name, sub, path)
                fi.nested[sub.name] = nested
        return fi

    @staticmethod
    def _from_base(modname: str, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        # relative import: drop `level` trailing components of the module
        base = ".".join(modname.split(".")[:-node.level])
        if node.module:
            base = base + "." + node.module if base else node.module
        return base

    # -- lookup ----------------------------------------------------------

    def _symbol(self, dotted: str):
        """A dotted import target -> FuncInfo (function) or
        ("class", module, name) or None."""
        if dotted in self.modules:
            return None
        head, _, tail = dotted.rpartition(".")
        mod = self.modules.get(head)
        if mod is None:
            return None
        if tail in mod.functions:
            return mod.functions[tail]
        if tail in mod.classes:
            return ("class", mod.name, tail)
        return None

    def class_method(self, module: str, klass: str, meth: str):
        """Method lookup walking same-module (or imported) bases."""
        seen = set()
        queue = [(module, klass)]
        while queue:
            m, k = queue.pop(0)
            if (m, k) in seen:
                continue
            seen.add((m, k))
            mod = self.modules.get(m)
            if mod is None:
                continue
            fi = mod.classes.get(k, {}).get(meth)
            if fi is not None:
                return fi
            for base in mod.bases.get(k, ()):
                tgt = mod.imports.get(base)
                if tgt is not None:
                    sym = self._symbol(tgt)
                    if isinstance(sym, tuple):
                        queue.append((sym[1], sym[2]))
                else:
                    queue.append((m, base))
        return None

    def constructor(self, module: str, klass: str):
        """__init__ of a class, or None (dataclass-style implicit init)."""
        return self.class_method(module, klass, "__init__")

    def resolve(self, call: ast.Call, caller: FuncInfo,
                recv_types: set | None = None
                ) -> list[tuple[FuncInfo | None, bool, str | None]]:
        """Call targets as (info, is_constructor, class_name) triples.

        A constructor target with no explicit __init__ (dataclasses)
        yields (None, True, ClassName) so callers can still model
        "tainted args -> tainted instance".  `recv_types` — inferred
        class names of a method call's receiver — makes `obj.m()`
        resolution exact; without it, name-devirtualization kicks in
        for uncommon method names only.
        """
        f = call.func
        mod = self.modules.get(caller.module)
        if mod is None:
            return []
        if isinstance(f, ast.Name):
            if f.id in caller.nested:
                return [(caller.nested[f.id], False, None)]
            if f.id in mod.functions:
                return [(mod.functions[f.id], False, None)]
            if f.id in mod.classes:
                init = self.constructor(mod.name, f.id)
                return [(init, True, f.id)]
            tgt = mod.imports.get(f.id)
            if tgt is not None:
                sym = self._symbol(tgt)
                if isinstance(sym, FuncInfo):
                    return [(sym, False, None)]
                if isinstance(sym, tuple):
                    init = self.constructor(sym[1], sym[2])
                    return [(init, True, sym[2])]
            return []
        if not isinstance(f, ast.Attribute):
            return []
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "self" and caller.klass is not None:
                hit = self.class_method(caller.module, caller.klass, f.attr)
                if hit is not None:
                    return [(hit, False, None)]
                return []
            tgt = mod.imports.get(base.id)
            if tgt is not None and tgt in self.modules:
                other = self.modules[tgt]
                if f.attr in other.functions:
                    return [(other.functions[f.attr], False, None)]
                if f.attr in other.classes:
                    init = self.constructor(other.name, f.attr)
                    return [(init, True, f.attr)]
                return []
        if recv_types:
            out = []
            for cls in sorted(recv_types):
                for cmod, cname in self.classes_by_name.get(cls, ()):
                    hit = self.class_method(cmod, cname, f.attr)
                    if hit is not None:
                        out.append((hit, False, None))
            if out:
                return out
        if f.attr in BUILTIN_METHODS:
            return []
        cands = self.methods_by_name.get(f.attr, [])
        if 0 < len(cands) <= DEVIRT_MAX:
            return [(c, False, None) for c in cands]
        return []


def get_callgraph(ctx) -> CallGraph:
    bucket = ctx.bucket("callgraph")
    if "graph" not in bucket or bucket.get("nfiles") != len(ctx.files):
        bucket["graph"] = CallGraph(ctx.files)
        bucket["nfiles"] = len(ctx.files)
    return bucket["graph"]
