"""Config-key schema checks: every tsd.* read must be a declared key.

`opentsdb_tpu/utils/config.py` declares `CONFIG_SCHEMA` (key -> type,
default, doc).  This analyzer holds the codebase to it:

  config-unknown-key     a `tsd.*` literal passed to a Config getter
                         (get_string / get_int / get_float / get_bool /
                         get_directory_name / has_property /
                         override_config), or a module-level `tsd.*`
                         string constant (the CONFIG_KEY / key-table
                         idiom), names no declared key — a typo'd key
                         reads the default forever and misconfigures
                         silently.
  config-type-mismatch   the getter's type disagrees with the schema
                         (`get_bool` on an int key answers False for
                         every nonzero value...).  `get_string` is the
                         raw accessor and is allowed on any key.
  config-dead-key        a schema entry (not marked compat) that no
                         scanned code reads — stale registry entries
                         hide real keys.  Whole-program pass; only runs
                         when the scan includes utils/config.py.

Module-level constants count as *reads* for the dead-key pass (the
whitelist/_KEYS idiom reads them through a variable), and they are only
checked in modules matching the key-constant idiom — string constants
at module scope whose value starts with "tsd.".
"""

from __future__ import annotations

import ast

from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_UNKNOWN = "config-unknown-key"
RULE_TYPE = "config-type-mismatch"
RULE_DEAD = "config-dead-key"

# getter -> type it imposes (None = type-neutral)
GETTERS: dict[str, str | None] = {
    "get_string": None,
    "get_int": "int",
    "get_float": "float",
    "get_bool": "bool",
    "get_directory_name": "dir",
    "has_property": None,
    "override_config": None,
}

# schema type -> typed getters allowed (get_string always allowed)
_ALLOWED = {
    "str": {"get_directory_name"},
    "dir": {"get_directory_name"},
    "int": {"get_int", "get_float"},
    "float": {"get_float"},
    "bool": {"get_bool"},
}


def _load_schema(ctx: LintContext) -> tuple[dict[str, str], set[str]]:
    """(key -> type, compat keys).  Tests inject via
    ctx.bucket("config")["schema"] / ["compat"]."""
    bucket = ctx.bucket("config")
    if "schema" not in bucket:
        from opentsdb_tpu.utils.config import CONFIG_SCHEMA
        bucket["schema"] = {k: e.type for k, e in CONFIG_SCHEMA.items()}
        bucket["compat"] = {k for k, e in CONFIG_SCHEMA.items() if e.compat}
    return bucket["schema"], bucket.get("compat", set())


def _is_key(value) -> bool:
    return isinstance(value, str) and value.startswith("tsd.") \
        and len(value) > 4


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    schema, _compat = _load_schema(ctx)
    bucket = ctx.bucket("config")
    read = bucket.setdefault("read_keys", set())
    out: list[Finding] = []

    # declaration-site lines for the dead-key pass
    if src.path.endswith("utils/config.py"):
        bucket["config_py"] = src

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        getter = node.func.attr
        if getter not in GETTERS or not node.args:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and _is_key(arg.value)):
            continue
        key = arg.value
        read.add(key)
        if key not in schema:
            out.append(Finding(
                src.path, node.lineno, RULE_UNKNOWN,
                "config key '%s' (via %s) is not declared in "
                "CONFIG_SCHEMA" % (key, getter)))
            continue
        imposed = GETTERS[getter]
        if imposed is not None and \
                getter not in _ALLOWED.get(schema[key], set()) and \
                imposed != schema[key]:
            out.append(Finding(
                src.path, node.lineno, RULE_TYPE,
                "%s() on config key '%s' which is declared '%s' in "
                "CONFIG_SCHEMA" % (getter, key, schema[key])))

    # module-level tsd.* string constants (CONFIG_KEY / key-table idiom):
    # bare literals and literals inside dict/tuple/list displays.  Call
    # arguments are excluded — logging.getLogger("tsd.rpc") names a
    # logger, not a key.  obs/__init__.py is excluded like config.py:
    # its METRICS_SCHEMA table declares tsd.* METRIC names (their own
    # analyzer, metrics_schema), not config keys.
    if not src.path.endswith(("utils/config.py", "obs/__init__.py")):
        for stmt in src.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            for node in _non_call_constants(stmt.value):
                if _is_key(node.value):
                    read.add(node.value)
                    if node.value not in schema:
                        out.append(Finding(
                            src.path, node.lineno, RULE_UNKNOWN,
                            "module-level config key constant '%s' is "
                            "not declared in CONFIG_SCHEMA" % node.value))

    # every other tsd.* literal (stats metric names, keys passed through
    # variables into getters, doc strings) counts as a *read* for the
    # dead-key pass — a key mentioned anywhere is not dead — without
    # being checked for membership (metric names are not config keys).
    # utils/config.py is excluded: a schema entry's own declaration
    # literal must not count as a read, or dead keys could never exist.
    if not src.path.endswith("utils/config.py"):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and _is_key(node.value):
                read.add(node.value)
    return out


def _non_call_constants(root: ast.expr | None):
    """String constants reachable without entering a Call subtree."""
    if root is None:
        return
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            continue
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node
            continue
        stack.extend(ast.iter_child_nodes(node))


def finish(ctx: LintContext) -> list[Finding]:
    bucket = ctx.bucket("config")
    config_src = bucket.get("config_py")
    if config_src is None:
        return []        # partial scan (fixtures): no dead-key verdicts
    schema, compat = _load_schema(ctx)
    read = bucket.get("read_keys", set())
    out: list[Finding] = []
    for key in sorted(schema):
        if key in read or key in compat:
            continue
        line = 0
        needle = '"%s"' % key
        for i, text in enumerate(config_src.lines, start=1):
            if needle in text:
                line = i
                break
        out.append(Finding(
            config_src.path, line, RULE_DEAD,
            "config key '%s' is declared in CONFIG_SCHEMA but never read "
            "by any scanned code (mark compat=True if it is accepted for "
            "reference-config compatibility)" % key))
    return out


ANALYZER = Analyzer(
    "config_schema", (RULE_UNKNOWN, RULE_TYPE, RULE_DEAD), check, finish)
