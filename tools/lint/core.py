"""tsdblint framework: findings, suppressions, baseline, runner.

Design choices, in the order they bit previous linters:

  * Baseline entries are keyed by (path, rule, message) — NOT line
    numbers — so unrelated edits above a grandfathered finding don't
    churn the baseline file.  Messages therefore never embed line
    numbers; duplicates within a file carry a count.
  * Suppressions are source comments (`# tsdblint: disable=rule[,rule]`)
    on the flagged line or the line directly above it, plus a file-level
    form (`# tsdblint: disable-file=rule`) honored anywhere in the first
    20 lines.  Suppressing should be a visible, reviewable act.
  * Analyzers are two-phase: `check(file)` per parsed file, `finish()`
    once after the walk for whole-program rules (lock-order cycles,
    dead config keys).  Both phases emit Finding objects.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import time
from typing import Callable, Iterable

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SUPPRESS_MARK = "tsdblint: disable="
SUPPRESS_FILE_MARK = "tsdblint: disable-file="
FILE_MARK_SCAN_LINES = 20


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation.  `message` must be line-number-free (it is
    the baseline identity together with path and rule)."""
    path: str       # repo-relative, posix separators
    line: int       # 1-based; 0 for whole-file findings
    rule: str
    message: str
    # Interprocedural route to the finding: ((path, line, note), ...).
    # Excluded from identity — the baseline and suppression story is
    # unchanged; SARIF renders these as relatedLocations.
    related: tuple = dataclasses.field(default=(), compare=False)

    @property
    def fingerprint(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.message)

    def render(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.rule,
                                   self.message)


class SourceFile:
    """A parsed source file handed to each analyzer."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        with open(abspath, "r", encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=relpath)
        self._suppressed = self._parse_suppressions()
        self._file_suppressed = self._parse_file_suppressions()

    # -- suppressions --

    def _parse_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            pos = line.find(SUPPRESS_MARK)
            if pos < 0:
                continue
            rules = line[pos + len(SUPPRESS_MARK):].split("#")[0]
            names = {r.strip() for r in rules.split(",") if r.strip()}
            out.setdefault(i, set()).update(names)
        return out

    def _parse_file_suppressions(self) -> set[str]:
        out: set[str] = set()
        for line in self.lines[:FILE_MARK_SCAN_LINES]:
            pos = line.find(SUPPRESS_FILE_MARK)
            if pos < 0:
                continue
            rules = line[pos + len(SUPPRESS_FILE_MARK):].split("#")[0]
            out.update(r.strip() for r in rules.split(",") if r.strip())
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        if rule in self._file_suppressed:
            return True
        for at in (line, line - 1):
            if rule in self._suppressed.get(at, set()):
                return True
        return False


class LintContext:
    """Shared state across files and analyzers (whole-program passes)."""

    def __init__(self, root: str = REPO_ROOT):
        self.root = root
        self.data: dict = {}       # analyzer-namespaced scratch space
        self.files: list[SourceFile] = []

    def bucket(self, name: str) -> dict:
        return self.data.setdefault(name, {})


class Analyzer:
    """One named analyzer: per-file check + optional whole-program finish."""

    def __init__(self, name: str, rules: tuple[str, ...],
                 check: Callable[[SourceFile, LintContext], list[Finding]],
                 finish: Callable[[LintContext], list[Finding]] | None = None):
        self.name = name
        self.rules = rules
        self.check = check
        self.finish = finish


def _iter_py_files(paths: Iterable[str], root: str) -> list[str]:
    out: list[str] = []
    for path in paths:
        abspath = path if os.path.isabs(path) else os.path.join(root, path)
        if os.path.isfile(abspath):
            out.append(abspath)
            continue
        for dirpath, dirnames, filenames in os.walk(abspath):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def get_analyzers() -> list[Analyzer]:
    """All fifteen analyzers (imported lazily so `core` has no
    circulars).

    The PR-2 four are per-file; the v2 three (shape/dtype abstract
    interpretation, request-field taint, resource-leak paths) run over
    the interprocedural call graph built once per LintContext, as do
    the v3 cache-coherence pass, the v4 pair (deadline discipline +
    hold-lock-while-blocking, tools/lint/blocking.py), the v5
    order-contract pass (tools/lint/ordering.py), and the v6 pair
    (effect contracts + explain dispatch purity, tools/lint/effects.py).
    metrics_schema is per-file like config_schema, as is v5's
    failure_atomicity."""
    from tools.lint import (blocking, cache_coherence, config_schema,
                            effects, exception_discipline, jax_hygiene,
                            lock_discipline, metrics_schema, ordering,
                            resource_leak, shape_dtype, taint)
    return [jax_hygiene.ANALYZER, lock_discipline.ANALYZER,
            config_schema.ANALYZER, metrics_schema.ANALYZER,
            exception_discipline.ANALYZER, shape_dtype.ANALYZER,
            taint.ANALYZER, resource_leak.ANALYZER,
            cache_coherence.ANALYZER, blocking.DEADLINE_ANALYZER,
            blocking.HOLD_LOCK_ANALYZER, ordering.ORDER_ANALYZER,
            ordering.ATOMICITY_ANALYZER, effects.EFFECT_ANALYZER,
            effects.PURITY_ANALYZER]


ALL_ANALYZERS = get_analyzers


def run_lint(paths: Iterable[str], root: str = REPO_ROOT,
             analyzers: list[Analyzer] | None = None,
             ctx: LintContext | None = None) -> list[Finding]:
    """Run analyzers over `paths`; returns suppression-filtered findings
    in (path, line, rule) order.  Syntax errors surface as `parse-error`
    findings rather than crashing the run."""
    if analyzers is None:
        analyzers = get_analyzers()
    if ctx is None:
        ctx = LintContext(root)
    timings = ctx.bucket("timings")
    findings: list[Finding] = []
    for abspath in _iter_py_files(paths, root):
        rel = os.path.relpath(abspath, root)
        try:
            src = SourceFile(abspath, rel)
        except SyntaxError as e:
            findings.append(Finding(rel.replace(os.sep, "/"),
                                    e.lineno or 0, "parse-error", str(e)))
            continue
        ctx.files.append(src)
        for analyzer in analyzers:
            t0 = time.perf_counter()
            checked = analyzer.check(src, ctx)
            timings[analyzer.name] = timings.get(analyzer.name, 0.0) \
                + (time.perf_counter() - t0)
            for f in checked:
                if not src.suppressed(f.line, f.rule):
                    findings.append(f)
    by_path = {src.path: src for src in ctx.files}
    for analyzer in analyzers:
        if analyzer.finish is None:
            continue
        t0 = time.perf_counter()
        finished = analyzer.finish(ctx)
        timings[analyzer.name] = timings.get(analyzer.name, 0.0) \
            + (time.perf_counter() - t0)
        for f in finished:
            src = by_path.get(f.path)
            if src is not None and src.suppressed(f.line, f.rule):
                continue
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                           f.message))


# --------------------------------------------------------------------- #
# Baseline                                                              #
# --------------------------------------------------------------------- #

BASELINE_VERSION = 1


def save_baseline(findings: list[Finding], path: str) -> None:
    """Line-number-free, sorted, deduplicated-with-counts — re-running
    over an unchanged tree must reproduce the file byte-for-byte."""
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    entries = [{"path": p, "rule": r, "message": m, "count": c}
               for (p, r, m), c in sorted(counts.items())]
    payload = {"version": BASELINE_VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> dict[tuple[str, str, str], int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    return {(e["path"], e["rule"], e["message"]): int(e.get("count", 1))
            for e in payload.get("findings", [])}


def apply_baseline(findings: list[Finding],
                   baseline: dict[tuple[str, str, str], int]
                   ) -> list[Finding]:
    """Subtract grandfathered findings.  Each baseline entry absorbs up
    to `count` identical findings; the excess (a NEW violation of an old
    shape) still reports."""
    budget = dict(baseline)
    fresh: list[Finding] = []
    for f in findings:
        left = budget.get(f.fingerprint, 0)
        if left > 0:
            budget[f.fingerprint] = left - 1
        else:
            fresh.append(f)
    return fresh
