"""Effect & purity contracts: static verification of the read-only
consult surface (tsdblint v6).

Two interprocedural analyzers share one whole-program pass over the
PR 3 call graph:

`effect_contract` — infers a per-function EFFECT SUMMARY to a fixpoint
over call edges and checks it against the `# effects:` grammar
(tools/lint/annotations.py).  Modeled effect classes:

    write      assignment/augmented-assignment/delete of a `self`
               attribute (including mutator-method calls — pop, update,
               clear, append, move_to_end... — on a self attribute), and
               rebinding of a `global`-declared module name.  A global
               rebound only under its own emptiness check
               (`if _CACHE is None:`) is a lazy-init memoization store
               and is sanctioned.  `__init__` writing its own instance
               is construction, not mutation (same exemption as
               lock_discipline and tsdbsan).
    counter    a call chain rooted at the `REGISTRY` name ending in
               inc/dec/observe/set — prometheus counter/histogram/gauge
               bumps (flight-recorder and jaxprof accounting reach this
               class transitively through their own bodies).
    lock       `with self._lock:` on a declared lock attribute (shared
               ClassAnnotations), or `.acquire()` on one.
    dispatch   a call rooted at the `jax`/`jnp` names, a call resolving
               to the dispatch-gateway set (the exact functions
               test_explain.py booby-traps), or a call of a module-level
               `X = jax.jit(...)` binding.
    permit     `.acquire(...)` on anything that is NOT a declared lock
               attribute (admission permits block on capacity — an
               explain or pure route must never take one), or any call
               resolving into AdmissionGate.acquire.

Summaries carry per-effect GATE SETS: an effect incurred under
`if observe:` (or after an `if not observe: return` guard, or through a
`refuse = real_fn if observe else (lambda...)` alias) is gated by
`observe`.  At a call site the callee's gates map through the argument:
passing a literal False drops the gated effects (the dry-run arm),
passing one of the caller's own parameters re-gates them on it, and
anything else conservatively promotes them to unconditional.  The
fixpoint is union-only over a finite effect alphabet, so it converges.

Contracts:  `pure` forbids everything; `reads-only` allows locks only;
`observe-gated(p)` additionally allows write/counter effects gated by
`p` (a leak of an ungated accounting effect is the dedicated
`effect-observe-leak` rule — the one that fires when someone moves a
demand observation out of the `if observe:` arm); `canonicalize`
allows writes confined to the function's own class (Series
normalization) and is how a value-preserving re-canonicalization is
treated as a read by callers — the claim is itself verified here, not
trusted.

`dispatch_purity` — tree-level reachability: from the /api/query/explain
entry (`QueryRpc.handle_explain`) and every `# effects: pure` function,
walk ONLY unambiguous call edges (ordering's rule: an ambiguous
devirtualization must not invent reachability) and report any dispatch
(`dispatch-reachable`) or permit acquisition (`permit-reachable`) site
in the closure.  This is deliberately redundant with `effect_contract`
— the contracts guard the annotated arms under full union resolution,
the reachability walk guards the whole explain subtree — so injecting a
`jnp` call or a `permit.acquire` anywhere under handle_explain fails
lint even if no annotated function is touched.

tsdbsan's explain-sentinel (tools/sanitize/effects.py) is the dynamic
twin: `static_effect_table()` exports the contract table + watched
classes the runtime cross-checks armed-request events against.
"""

from __future__ import annotations

import ast

from tools.lint.annotations import effects_annotation
from tools.lint.astindex import get_ast_index
from tools.lint.callgraph import get_callgraph, module_name
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_VIOLATION = "effect-violation"
RULE_LEAK = "effect-observe-leak"
RULE_BAD = "effect-bad-annotation"
RULE_DISPATCH = "dispatch-reachable"
RULE_PERMIT = "permit-reachable"

EFFECT_DIRS = ("opentsdb_tpu/",)

# The /api/query/explain entry: everything reachable from here through
# unambiguous call edges must be dispatch- and permit-free.
ENTRY_QNAMES = ("opentsdb_tpu.tsd.rpcs.QueryRpc.handle_explain",)

# The exact gateway set tests/test_explain.py booby-traps: every device
# dispatch in the query path funnels through one of these.
DISPATCH_GATEWAYS = frozenset({
    "opentsdb_tpu.ops.pipeline.run_pipeline",
    "opentsdb_tpu.ops.pipeline.run_group_pipeline",
    "opentsdb_tpu.ops.pipeline.run_union_batch_pipeline",
    "opentsdb_tpu.ops.pipeline.run_grid_tail",
    "opentsdb_tpu.ops.pipeline.run_downsample_grid",
    "opentsdb_tpu.ops.pipeline.build_batch",
    "opentsdb_tpu.ops.pipeline.build_batch_direct",
    "opentsdb_tpu.ops.tiling.run_tiled",
    "opentsdb_tpu.storage.device_cache._gather_windows",
    "opentsdb_tpu.ops.streaming.StreamAccumulator.create",
})

PERMIT_QNAMES = frozenset({
    "opentsdb_tpu.tsd.admission.AdmissionGate.acquire",
})

_JAX_ROOTS = frozenset({"jax", "jnp"})

# `jax.*` calls that interrogate device topology or configure the
# runtime rather than dispatching compute.  The explain path is allowed
# to ask WHICH backend will serve a plan (platform pricing needs it) —
# it must never hand the backend work.  `jnp.*` is always compute.
_JAX_METADATA = frozenset({
    "devices", "local_devices", "device_count", "local_device_count",
    "default_backend", "process_index", "process_count",
})
_JAX_INFRA_NS = frozenset({"config", "distributed"})
_COUNTER_TAILS = frozenset({"inc", "dec", "observe", "set"})
_MUTATORS = frozenset({
    "pop", "popitem", "clear", "update", "setdefault", "append",
    "appendleft", "extend", "extendleft", "add", "remove", "discard",
    "insert", "sort", "reverse", "move_to_end",
})

_SANCTIONED = {"write", "counter"}      # gateable accounting classes


# --------------------------------------------------------------------- #
# Effect summaries                                                      #
# --------------------------------------------------------------------- #
#
# A summary maps (kind, detail) -> _Eff.  `gates` is the set of boolean
# parameter names that must ALL be truthy for the effect to fire — an
# empty set means unconditional.  Merging two occurrences intersects
# the gates (the effect fires if either occurrence does), which only
# shrinks — together with the grow-only effect set this makes the
# interprocedural fixpoint monotone.

class _Eff:
    __slots__ = ("gates", "site", "origin", "via")

    def __init__(self, gates: frozenset, site: tuple,
                 origin: tuple, via: str | None = None):
        self.gates = gates
        self.site = site            # (path, line) where incurred locally
        self.origin = origin        # (path, line) of the primitive effect
        self.via = via              # callee qname it arrived through

    def merged(self, other: "_Eff") -> "_Eff":
        gates = self.gates & other.gates
        keep = self if len(self.gates) <= len(other.gates) else other
        if gates == keep.gates:
            return keep
        return _Eff(gates, keep.site, keep.origin, keep.via)


class _CallSite:
    __slots__ = ("call", "targets", "gates", "force_gates")

    def __init__(self, call: ast.Call, targets: list, gates: frozenset,
                 force_gates: frozenset | None = None):
        self.call = call
        self.targets = targets      # list[FuncInfo]
        self.gates = gates          # ambient gates at the call site
        self.force_gates = force_gates  # gated-callable alias (IfExp)


def _root_name(expr) -> str | None:
    """The leftmost Name of an attribute/call chain, or None."""
    while True:
        if isinstance(expr, ast.Attribute):
            expr = expr.value
        elif isinstance(expr, ast.Call):
            expr = expr.func
        elif isinstance(expr, ast.Subscript):
            expr = expr.value
        elif isinstance(expr, ast.Name):
            return expr.id
        else:
            return None


def _self_attr(expr) -> str | None:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _self_attr_target(target) -> str | None:
    """The self attribute a write target lands on, seeing through
    subscripts (`self._blocks[key] = ...` writes `_blocks`)."""
    while isinstance(target, ast.Subscript):
        target = target.value
    return _self_attr(target)


class _FnScan:
    """Direct effects + call sites of one function body."""

    def __init__(self, an: "_Analysis", fi, src: SourceFile, cls):
        self.an = an
        self.fi = fi
        self.src = src
        self.cls = cls              # ClassAnnotations or None
        self.effects: dict[tuple[str, str], _Eff] = {}
        self.calls: list[_CallSite] = []
        self.globals: set[str] = set()
        self.aliases: dict[str, tuple[frozenset, ast.expr]] = {}
        a = fi.node.args
        self.params = frozenset(
            p.arg for p in a.posonlyargs + a.args + a.kwonlyargs)
        self.is_init = fi.name == "__init__"
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Global):
                self.globals.update(node.names)
        self.visit_block(fi.node.body, frozenset(), frozenset())

    # -- recording --------------------------------------------------------

    def add(self, kind: str, detail: str, gates: frozenset,
            line: int) -> None:
        key = (kind, detail)
        eff = _Eff(gates, (self.src.path, line), (self.src.path, line))
        cur = self.effects.get(key)
        self.effects[key] = eff if cur is None else cur.merged(eff)

    def _write_detail(self, attr: str) -> str:
        owner = self.fi.klass or module_name(self.src.path)
        return "%s.%s" % (owner, attr)

    # -- statement walk ---------------------------------------------------

    def visit_block(self, stmts, gates: frozenset,
                    sanctioned: frozenset) -> None:
        """`gates` = observe-style parameter guards dominating this
        block; `sanctioned` = global names whose lazy-init store is
        currently allowed (inside their own `is None` check)."""
        gates_now = gates
        for st in stmts:
            self.visit_stmt(st, gates_now, sanctioned)
            # `if not observe: return` dominates the rest of the block
            g = self._early_out_gate(st)
            if g is not None:
                gates_now = gates_now | {g}
            # `if _LOADED: return ...` on a global flag: the rest of
            # the block runs once per process — its global stores are
            # lazy-init memoization, not effects
            if self._once_only_guard(st):
                sanctioned = sanctioned | self.globals

    def _early_out_gate(self, st) -> str | None:
        if not isinstance(st, ast.If) or st.orelse:
            return None
        t = st.test
        if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not) \
                and isinstance(t.operand, ast.Name) \
                and t.operand.id in self.params \
                and st.body and isinstance(st.body[-1],
                                           (ast.Return, ast.Raise,
                                            ast.Continue)):
            return t.operand.id
        return None

    def _once_only_guard(self, st) -> bool:
        if not isinstance(st, ast.If) or st.orelse:
            return False
        if not (st.body and isinstance(st.body[-1], ast.Return)):
            return False
        t = st.test
        if isinstance(t, ast.Name):
            return t.id in self.globals
        return isinstance(t, ast.Compare) and len(t.ops) == 1 \
            and isinstance(t.ops[0], ast.IsNot) \
            and isinstance(t.left, ast.Name) \
            and t.left.id in self.globals \
            and isinstance(t.comparators[0], ast.Constant) \
            and t.comparators[0].value is None

    def _test_gates(self, test) -> frozenset:
        """Parameter names a positive branch of `test` is gated by."""
        names: set[str] = set()
        exprs = test.values if isinstance(test, ast.BoolOp) and \
            isinstance(test.op, ast.And) else [test]
        for e in exprs:
            if isinstance(e, ast.Name) and e.id in self.params:
                names.add(e.id)
        return frozenset(names)

    def _lazy_init_names(self, test) -> frozenset:
        """Global names whose rebinding under this test is a sanctioned
        lazy-init store: `if G is None:` / `if not G:` / `if G is None
        or ...`."""
        names: set[str] = set()
        exprs = test.values if isinstance(test, ast.BoolOp) else [test]
        for e in exprs:
            if isinstance(e, ast.Compare) and len(e.ops) == 1 \
                    and isinstance(e.ops[0], ast.Is) \
                    and isinstance(e.left, ast.Name) \
                    and isinstance(e.comparators[0], ast.Constant) \
                    and e.comparators[0].value is None:
                names.add(e.left.id)
            elif isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not) \
                    and isinstance(e.operand, ast.Name):
                names.add(e.operand.id)
        return frozenset(names & self.globals)

    def visit_stmt(self, st, gates: frozenset,
                   sanctioned: frozenset) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                  # nested defs contribute when called
        if isinstance(st, ast.If):
            pos = gates | self._test_gates(st.test)
            body_sanction = sanctioned | self._lazy_init_names(st.test)
            self.visit_block(st.body, pos, body_sanction)
            self.visit_block(st.orelse, gates, sanctioned)
            self.scan_exprs([st.test], gates, sanctioned)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and self._is_lock_attr(attr):
                    self.add("lock", self._write_detail(attr),
                             frozenset(), item.context_expr.lineno)
                else:
                    self.scan_exprs([item.context_expr], gates,
                                    sanctioned)
            self.visit_block(st.body, gates, sanctioned)
            return
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            ctrl = getattr(st, "iter", None) or getattr(st, "test", None)
            self.scan_exprs([ctrl], gates, sanctioned)
            self.visit_block(st.body, gates, sanctioned)
            self.visit_block(st.orelse, gates, sanctioned)
            return
        if isinstance(st, ast.Try):
            self.visit_block(st.body, gates, sanctioned)
            for h in st.handlers:
                self.visit_block(h.body, gates, sanctioned)
            self.visit_block(st.orelse, gates, sanctioned)
            self.visit_block(st.finalbody, gates, sanctioned)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._visit_assign(st, gates, sanctioned)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                attr = _self_attr_target(t)
                if attr is not None and not self.is_init:
                    self.add("write", self._write_detail(attr), gates,
                             st.lineno)
            return
        self.scan_exprs([st], gates, sanctioned)

    def _visit_assign(self, st, gates: frozenset,
                      sanctioned: frozenset) -> None:
        targets = st.targets if isinstance(st, ast.Assign) else \
            [st.target]
        for t in targets:
            parts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for p in parts:
                attr = _self_attr_target(p)
                if attr is not None:
                    if not (self.is_init or self._is_lock_decl(st)):
                        self.add("write", self._write_detail(attr),
                                 gates, st.lineno)
                elif isinstance(p, ast.Name) and p.id in self.globals \
                        and p.id not in sanctioned:
                    self.add("write", "%s.%s"
                             % (module_name(self.src.path), p.id),
                             gates, st.lineno)
        value = getattr(st, "value", None)
        # `refuse = count_refusal if observe else (lambda...)`: calls of
        # the alias are gated by the test parameter
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(value, ast.IfExp):
            g = self._test_gates(value.test)
            if g and isinstance(value.body, (ast.Name, ast.Attribute)):
                self.aliases[st.targets[0].id] = (gates | g, value.body)
                self.scan_exprs([value.orelse], gates, sanctioned)
                return
        self.scan_exprs([value], gates, sanctioned)

    @staticmethod
    def _is_lock_decl(st) -> bool:
        value = getattr(st, "value", None)
        return isinstance(value, ast.Call) and \
            _root_name(value.func) in ("threading",) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in ("Lock", "RLock"))

    def _is_lock_attr(self, attr: str) -> bool:
        if self.cls is not None and attr in self.cls.locks:
            return True
        return "lock" in attr.lower()

    # -- expression scan (calls) ------------------------------------------

    def scan_exprs(self, exprs, gates: frozenset,
                   sanctioned: frozenset) -> None:
        for e in exprs:
            if e is None:
                continue
            for node in ast.walk(e):
                if isinstance(node, (ast.Lambda,)):
                    continue
                if isinstance(node, ast.Call):
                    self._visit_call(node, gates)

    def _visit_call(self, call: ast.Call, gates: frozenset) -> None:
        f = call.func
        root = _root_name(f)
        if isinstance(f, ast.Attribute):
            if root in _JAX_ROOTS:
                if not self._jax_metadata(f, root):
                    self.add("dispatch", "%s.%s" % (root, f.attr),
                             gates, call.lineno)
                return
            if f.attr in _COUNTER_TAILS and root == "REGISTRY":
                self.add("counter", self._metric_name(call), gates,
                         call.lineno)
                return
            if f.attr == "acquire":
                attr = _self_attr(f.value)
                if attr is not None and self._is_lock_attr(attr):
                    self.add("lock", self._write_detail(attr), gates,
                             call.lineno)
                elif root is not None and "lock" in root.lower():
                    self.add("lock", root, gates, call.lineno)
                else:
                    self.add("permit",
                             ast.unparse(f.value)
                             if hasattr(ast, "unparse") else "acquire",
                             gates, call.lineno)
                return
            attr = _self_attr(f.value)
            if attr is not None and f.attr in _MUTATORS \
                    and not self.is_init:
                self.add("write", self._write_detail(attr), gates,
                         call.lineno)
                return
            # mutator on a deeper self chain: self._x[y].append(...)
            deep = _self_attr_target(f.value)
            if deep is not None and f.attr in _MUTATORS \
                    and not self.is_init:
                self.add("write", self._write_detail(deep), gates,
                         call.lineno)
                return
        if isinstance(f, ast.Name):
            alias = self.aliases.get(f.id)
            if alias is not None:
                force, target = alias
                fake = ast.Call(func=target, args=call.args,
                                keywords=call.keywords)
                ast.copy_location(fake, call)
                targets = [i for i, _c, _n in
                           self.an.graph.resolve(fake, self.fi)
                           if i is not None]
                if targets:
                    self.calls.append(_CallSite(call, targets,
                                                gates, force))
                return
            if self.an.is_jit_binding(self.fi.module, f.id):
                self.add("dispatch", "jit:%s" % f.id, gates,
                         call.lineno)
                return
        targets = [i for i, _c, _n in
                   self.an.graph.resolve(call, self.fi)
                   if i is not None]
        if targets:
            for info in targets:
                if info.qname in self.an.gateways:
                    self.add("dispatch", info.qname, gates, call.lineno)
                if info.qname in self.an.permit_qnames:
                    self.add("permit", info.qname, gates, call.lineno)
            self.calls.append(_CallSite(call, targets, gates))

    @staticmethod
    def _jax_metadata(f: ast.Attribute, root: str) -> bool:
        if root != "jax":
            return False
        if f.attr in _JAX_METADATA:
            return True
        chain = []
        node = f
        while isinstance(node, ast.Attribute):
            chain.append(node.attr)
            node = node.value
        # jax.config.update / jax.distributed.initialize: runtime
        # configuration, not compute
        return len(chain) >= 2 and chain[-1] in _JAX_INFRA_NS

    def _metric_name(self, call: ast.Call) -> str:
        for node in ast.walk(call):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "histogram",
                                           "gauge") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value
        return "REGISTRY"


# --------------------------------------------------------------------- #
# Whole-program pass                                                    #
# --------------------------------------------------------------------- #

_MAX_ROUNDS = 30


class _Analysis:
    def __init__(self, ctx: LintContext):
        bucket = ctx.bucket("effects")
        self.graph = get_callgraph(ctx)
        self.index = get_ast_index(ctx)
        self.dirs = tuple(bucket.get("paths", EFFECT_DIRS))
        self.entry_qnames = tuple(
            bucket.get("entry_qnames", ENTRY_QNAMES))
        self.gateways = frozenset(
            bucket.get("gateways", DISPATCH_GATEWAYS))
        self.permit_qnames = frozenset(
            bucket.get("permit_qnames", PERMIT_QNAMES))
        self.scans: dict[str, _FnScan] = {}
        self.summaries: dict[str, dict] = {}
        self.contracts: dict[str, tuple] = {}  # qname -> (contract, gate,
        #                                        fi, src, def line)
        self.bad: list[tuple] = []             # (fi, src, line, why)
        self._jit: dict[str, set[str]] = {}
        self.run(ctx)

    def in_scope(self, path: str) -> bool:
        return path.startswith(self.dirs) or \
            any(d in path for d in self.dirs)

    def is_jit_binding(self, module: str, name: str) -> bool:
        return name in self._jit.get(module, ())

    # -- annotation discovery ---------------------------------------------

    def _contract_for(self, fi, src: SourceFile):
        """The `# effects:` annotation attached to a def: inline on the
        def line, or on comment lines directly above it (decorators
        may sit in between)."""
        line = fi.node.lineno
        found = effects_annotation(src.lines[line - 1]) \
            if line <= len(src.lines) else None
        at = line
        if found is None:
            i = min(line, *[d.lineno for d in fi.node.decorator_list]) \
                if fi.node.decorator_list else line
            i -= 2                  # 0-based index of the line above
            while i >= 0:
                text = src.lines[i].strip()
                if text.startswith("@"):
                    i -= 1
                    continue
                if text.startswith("#"):
                    found = effects_annotation(text)
                    if found is not None:
                        at = i + 1
                        break
                    i -= 1
                    continue
                break
        return found, at

    # -- the pass ---------------------------------------------------------

    def run(self, ctx: LintContext) -> None:
        in_scope = [s for s in ctx.files if self.in_scope(s.path)]
        by_path = {s.path: s for s in in_scope}
        for src in in_scope:
            mod = module_name(src.path)
            jit = self._jit.setdefault(mod, set())
            for node in src.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and _root_name(node.value.func) in _JAX_ROOTS \
                        and isinstance(node.value.func, ast.Attribute) \
                        and node.value.func.attr == "jit":
                    jit.add(node.targets[0].id)
        # function scans (top-level + methods + one level of nesting)
        for src in in_scope:
            mod = self.graph.modules.get(module_name(src.path))
            if mod is None:
                continue
            fns = list(mod.functions.values())
            for methods in mod.classes.values():
                fns.extend(methods.values())
            for fi in fns:
                for nested in fi.nested.values():
                    self._scan(nested, src)
                self._scan(fi, src)
        # contract discovery
        for q, scan in self.scans.items():
            if ".<nested>." in q:
                continue
            fi, src = scan.fi, scan.src
            found, at = self._contract_for(fi, src)
            if found is None:
                continue
            contract, gate = found
            if contract == "observe-gated":
                if gate is None:
                    self.bad.append((fi, src, at,
                                     "observe-gated needs a parameter, "
                                     "e.g. observe-gated(observe)"))
                    continue
                if gate not in scan.params:
                    self.bad.append((fi, src, at,
                                     "gate parameter '%s' is not a "
                                     "parameter of this function"
                                     % gate))
                    continue
            elif gate is not None:
                self.bad.append((fi, src, at,
                                 "'%s' takes no gate parameter"
                                 % contract))
                continue
            self.contracts[q] = (contract, gate, fi, src, at)
        # interprocedural fixpoint
        for q, scan in self.scans.items():
            self.summaries[q] = dict(scan.effects)
        for _ in range(_MAX_ROUNDS):
            if not self._propagate_round():
                break

    def _scan(self, fi, src: SourceFile) -> None:
        cls = self.index.classes.get((src.path, fi.klass)) \
            if fi.klass else None
        self.scans[fi.qname] = _FnScan(self, fi, src, cls)

    def _propagate_round(self) -> bool:
        changed = False
        for q, scan in self.scans.items():
            summary = self.summaries[q]
            for site in scan.calls:
                for info in site.targets:
                    if self.contracts.get(info.qname, ("",))[0] \
                            == "canonicalize":
                        continue    # verified value-preserving: a read
                    callee = self.summaries.get(info.qname)
                    if not callee:
                        continue
                    if self._merge_call(summary, scan, site, info,
                                        callee):
                        changed = True
        return changed

    def _merge_call(self, summary, scan: _FnScan, site: _CallSite,
                    info, callee: dict) -> bool:
        mapping = self._gate_mapping(site, info)
        changed = False
        for key, eff in callee.items():
            gates: set[str] = set(site.gates)
            if site.force_gates:
                gates |= site.force_gates
            dropped = False
            for g in eff.gates:
                mapped = mapping.get(g, None)
                if mapped is _DROP:
                    dropped = True
                    break
                if mapped is not None:
                    gates.update(mapped)
                # mapped None: promoted — contributes no gate
            if dropped:
                continue
            new = _Eff(frozenset(gates),
                       (scan.src.path, site.call.lineno),
                       eff.origin, eff.via or info.qname)
            cur = summary.get(key)
            merged = new if cur is None else cur.merged(new)
            if cur is None or merged.gates != cur.gates:
                summary[key] = merged
                changed = True
        return changed

    def _gate_mapping(self, site: _CallSite, info) -> dict:
        """callee gate param -> _DROP | set of caller params | None
        (promote)."""
        call, params = site.call, info.params
        offset = 0
        if params and params[0] == "self" and (
                isinstance(call.func, ast.Attribute)
                or info.name == "__init__"):
            offset = 1              # positional args align past `self`
        out: dict = {}

        def classify(expr):
            if isinstance(expr, ast.Constant):
                return _DROP if not expr.value else None
            if isinstance(expr, ast.Name):
                return {expr.id}
            return None

        kw_params = set(params) | {a.arg for a in
                                   info.node.args.kwonlyargs}
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in kw_params:
                out[kw.arg] = classify(kw.value)
        for i, arg in enumerate(call.args):
            pi = i + offset
            if pi < len(params) and params[pi] not in out:
                out[params[pi]] = classify(arg)
        # unsupplied params fall back to their default
        args = info.node.args
        if args.defaults:
            named = [a.arg for a in args.posonlyargs + args.args]
            tail = named[len(named) - len(args.defaults):]
            for p, d in zip(tail, args.defaults):
                if p not in out and isinstance(d, ast.Constant) \
                        and not d.value:
                    out[p] = _DROP
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if a.arg not in out and isinstance(d, ast.Constant) \
                    and not d.value:
                out[a.arg] = _DROP
        return out


class _Drop:
    pass


_DROP = _Drop()


def _analysis(ctx: LintContext) -> _Analysis:
    bucket = ctx.bucket("effects")
    if "analysis" not in bucket \
            or bucket.get("nfiles") != len(ctx.files):
        bucket["analysis"] = _Analysis(ctx)
        bucket["nfiles"] = len(ctx.files)
    return bucket["analysis"]


# --------------------------------------------------------------------- #
# effect_contract: contract checking                                    #
# --------------------------------------------------------------------- #

def _related(an: _Analysis, eff: _Eff) -> tuple:
    """Related locations for one effect: the local site it was incurred
    at, the callee it arrived through, and the primitive origin."""
    out = [(eff.site[0], eff.site[1], "effect incurred here")]
    if eff.via is not None:
        info = an.graph.funcs.get(eff.via)
        if info is not None:
            out.append((info.path, info.node.lineno,
                        "via '%s'" % eff.via))
    if eff.origin != eff.site:
        out.append((eff.origin[0], eff.origin[1], "primitive effect"))
    return tuple(out)


def _check_contracts(ctx: LintContext) -> list[Finding]:
    an = _analysis(ctx)
    findings: list[Finding] = []
    for fi, src, line, why in an.bad:
        findings.append(Finding(src.path, line, RULE_BAD,
                                "malformed '# effects:' contract on "
                                "'%s': %s" % (fi.qname, why)))
    for q, (contract, gate, fi, src, _at) in sorted(an.contracts.items()):
        summary = an.summaries.get(q, {})
        for (kind, detail), eff in sorted(summary.items()):
            via = " (via '%s')" % eff.via if eff.via else ""
            rel = _related(an, eff)
            if contract == "pure":
                findings.append(Finding(
                    src.path, fi.node.lineno, RULE_VIOLATION,
                    "'%s' declares '# effects: pure' but has a %s "
                    "effect on '%s'%s" % (q, kind, detail, via),
                    related=rel))
                continue
            if kind == "lock" and contract in ("reads-only",
                                               "observe-gated",
                                               "canonicalize"):
                continue
            if contract == "reads-only":
                findings.append(Finding(
                    src.path, fi.node.lineno, RULE_VIOLATION,
                    "'%s' declares '# effects: reads-only' but has a "
                    "%s effect on '%s'%s" % (q, kind, detail, via),
                    related=rel))
                continue
            if contract == "canonicalize":
                own = fi.klass is not None and \
                    detail.startswith(fi.klass + ".")
                if kind == "write" and own:
                    continue
                findings.append(Finding(
                    src.path, fi.node.lineno, RULE_VIOLATION,
                    "'%s' declares '# effects: canonicalize' but has "
                    "a %s effect on '%s'%s — canonicalization may "
                    "only rewrite its own instance"
                    % (q, kind, detail, via), related=rel))
                continue
            # observe-gated(gate)
            if kind in _SANCTIONED:
                if gate in eff.gates:
                    continue
                findings.append(Finding(
                    src.path, fi.node.lineno, RULE_LEAK,
                    "'%s' declares '# effects: observe-gated(%s)' but "
                    "the %s effect on '%s' is not dominated by a "
                    "check of '%s'%s — the observe=False dry-run arm "
                    "would still mutate"
                    % (q, gate, kind, detail, gate, via), related=rel))
            else:
                findings.append(Finding(
                    src.path, fi.node.lineno, RULE_VIOLATION,
                    "'%s' declares '# effects: observe-gated(%s)' but "
                    "has a %s effect on '%s'%s — only gated "
                    "accounting is sanctioned, never %s"
                    % (q, gate, kind, detail, via, kind), related=rel))
    return findings


# --------------------------------------------------------------------- #
# dispatch_purity: tree-level reachability                              #
# --------------------------------------------------------------------- #

def _unique_callees(an: _Analysis, scan: _FnScan) -> list:
    """Unambiguous call targets only (ordering's rule): a call that
    devirtualizes to several candidates creates no reachability."""
    out = []
    for site in scan.calls:
        qnames = {i.qname for i in site.targets}
        if len(qnames) == 1:
            out.append(site.targets[0])
    return out


def _check_purity(ctx: LintContext) -> list[Finding]:
    an = _analysis(ctx)
    entries: list[str] = [q for q in an.entry_qnames if q in an.scans]
    for q, (contract, _g, _fi, _src, _at) in an.contracts.items():
        if contract == "pure" and q not in entries:
            entries.append(q)
    findings: list[Finding] = []
    reported: set[tuple] = set()
    for entry in sorted(entries):
        seen: set[str] = set()
        # qname -> (caller qname | None) for route reconstruction
        parent: dict[str, str | None] = {entry: None}
        queue = [entry]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            scan = an.scans.get(q)
            if scan is None:
                continue
            for (kind, detail), eff in sorted(scan.effects.items()):
                if kind not in ("dispatch", "permit"):
                    continue
                rule = RULE_DISPATCH if kind == "dispatch" \
                    else RULE_PERMIT
                key = (entry, q, kind, detail)
                if key in reported:
                    continue
                reported.add(key)
                chain = _route(parent, q)
                rel = tuple(
                    (an.scans[p].src.path, an.scans[p].fi.node.lineno,
                     "reached through '%s'" % p)
                    for p in chain if p in an.scans)
                what = "a device dispatch" if kind == "dispatch" \
                    else "an admission-permit acquisition"
                findings.append(Finding(
                    eff.site[0], eff.site[1], rule,
                    "%s ('%s') in '%s' is reachable from the "
                    "dispatch-free entry '%s' (route: %s)"
                    % (what, detail, q, entry, " -> ".join(chain)),
                    related=rel))
            for info in _unique_callees(an, scan):
                if info.qname not in seen:
                    parent.setdefault(info.qname, q)
                    queue.append(info.qname)
    return findings


def _route(parent: dict, q: str) -> list[str]:
    chain = [q]
    while parent.get(chain[-1]) is not None:
        chain.append(parent[chain[-1]])
    return list(reversed(chain))


# --------------------------------------------------------------------- #
# tsdbsan export                                                        #
# --------------------------------------------------------------------- #

def static_effect_table() -> dict:
    """{qname -> (contract, gate)} + the watched class set for the
    runtime explain-sentinel, from a fast standalone regex+AST scan of
    the default effect dirs (NOT a lint run — mirrors
    ordering.static_order_table)."""
    import os

    from tools.lint.core import REPO_ROOT
    contracts: dict[str, tuple] = {}
    watched: set[str] = set()
    for d in EFFECT_DIRS:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(REPO_ROOT, d)):
            dirnames[:] = [n for n in dirnames if n != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                rel = os.path.relpath(abspath, REPO_ROOT).replace(
                    os.sep, "/")
                try:
                    with open(abspath, "r", encoding="utf-8") as fh:
                        text = fh.read()
                except OSError:
                    continue
                if "# effects:" not in text:
                    continue
                try:
                    tree = ast.parse(text, filename=rel)
                except SyntaxError:
                    continue
                lines = text.splitlines()
                mod = module_name(rel)
                _table_from_tree(tree, lines, mod, contracts, watched)
    return {"contracts": contracts, "watched_classes": sorted(watched)}


def _table_from_tree(tree, lines, mod, contracts, watched) -> None:
    def visit(body, scope):
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit(node.body, scope + [node.name])
                continue
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            found = _def_annotation(lines, node)
            if found is None:
                continue
            contract, gate = found
            qname = ".".join([mod] + scope + [node.name])
            contracts[qname] = (contract, gate)
            if scope and contract in ("reads-only", "observe-gated"):
                watched.add(scope[-1])
    visit(tree.body, [])


def _def_annotation(lines, node):
    if node.lineno <= len(lines):
        found = effects_annotation(lines[node.lineno - 1])
        if found is not None:
            return found
    i = min([node.lineno] + [d.lineno for d in node.decorator_list]) - 2
    while i >= 0:
        text = lines[i].strip()
        if text.startswith("@"):
            i -= 1
            continue
        if text.startswith("#"):
            found = effects_annotation(text)
            if found is not None:
                return found
            i -= 1
            continue
        break
    return None


# --------------------------------------------------------------------- #
# Analyzers                                                             #
# --------------------------------------------------------------------- #

def _no_check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    return []


EFFECT_ANALYZER = Analyzer(
    "effect_contract", (RULE_VIOLATION, RULE_LEAK, RULE_BAD),
    _no_check, _check_contracts)

PURITY_ANALYZER = Analyzer(
    "dispatch_purity", (RULE_DISPATCH, RULE_PERMIT),
    _no_check, _check_purity)
