"""Exception discipline: broad excepts must log or count before swallowing.

A bare `except:` or `except Exception:` in a serving path that silently
swallows turns every novel failure into a ghost — the request "works",
the operator sees nothing, and the bug report arrives weeks later with
no trace.  One rule:

  except-swallow   a bare/broad except handler whose body neither
                   re-raises, returns/propagates an error object, logs
                   (a call through a logger-shaped name: LOG.exception,
                   logger.warning, ...), nor counts (a call to a
                   *count*/*record* method, or an in-place counter
                   increment).

Handlers that legitimately must stay silent (best-effort cleanup on an
already-failed path) carry a `# tsdblint: disable=except-swallow`
suppression with the justification in the comment — silence should be
visible in review.
"""

from __future__ import annotations

import ast

from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_SWALLOW = "except-swallow"

_BROAD = {"Exception", "BaseException"}
_LOGGERISH = ("log", "logger")
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_COUNTERISH = ("count", "record", "increment", "incr")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names: list[str] = []
    for node in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD for n in names)


def _logger_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in _LOG_METHODS:
        return False
    base = f.value
    name = None
    if isinstance(base, ast.Name):
        name = base.id
    elif isinstance(base, ast.Attribute):
        name = base.attr
    return name is not None and any(m in name.lower() for m in _LOGGERISH)


def _counter_call(node: ast.Call) -> bool:
    f = node.func
    attr = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    return attr is not None and any(m in attr.lower() for m in _COUNTERISH)


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler visibly deals with the failure."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.AugAssign):
            return True          # counter increment
        if isinstance(node, ast.Call) and (
                _logger_call(node) or _counter_call(node)):
            return True
        # handing the exception object onward (send_error(e),
        # errors.append((i, e)), return "...%s" % e) is handling too
        if isinstance(node, ast.Name) and handler.name is not None \
                and node.id == handler.name:
            return True
    return False


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _handles(node):
            continue
        # a suppression anywhere in the handler body counts — the
        # natural place for it is on the `pass`, not the `except` line
        end = max((getattr(s, "end_lineno", s.lineno)
                   for s in node.body), default=node.lineno)
        if any(src.suppressed(ln, RULE_SWALLOW)
               for ln in range(node.lineno, end + 1)):
            continue
        fn = "?"
        # enclosing function name for a line-free message
        for parent in ast.walk(src.tree):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(n is node for n in ast.walk(parent)):
                fn = parent.name
        out.append(Finding(
            src.path, node.lineno, RULE_SWALLOW,
            "broad except in '%s' swallows without logging or counting "
            "— log, count, re-raise, or suppress with a justification"
            % fn))
    return out


ANALYZER = Analyzer("exception_discipline", (RULE_SWALLOW,), check)
