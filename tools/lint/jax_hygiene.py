"""JAX kernel hygiene: host-sync and retrace hazards in jit-reachable code.

The 489.5M pts/s headline lives or dies on the `ops/` kernels staying
free of accidental device->host synchronization and per-call retracing.
Four rules:

  jax-host-sync          `.item()` / `.tolist()` / `float()` / `int()` /
                         `bool()` / `np.asarray()` applied to a traced
                         value inside a jit-reachable function — each one
                         blocks on the device and kills dispatch overlap.
  jax-tracer-branch      Python `if`/`while` on a traced value — a
                         ConcretizationError at best, a silent retrace
                         per distinct value at worst.
  jax-jit-per-call       `jax.jit(...)` constructed inside a function
                         body: a fresh jit wrapper per call path retraces
                         every time (module-scope construction, like
                         ops/pipeline.py's `_jitted`, compiles once).
                         Builders that memoize the wrapper in a cache
                         keyed by static shape are legitimate — suppress
                         with a comment explaining the cache.
  jax-int64-no-x64-guard `jnp.int64` in a module with no x64 guard in
                         sight (own `jax_enable_x64` update, an x64 guard
                         helper, or a package __init__ that enables x64):
                         with x64 disabled jnp.int64 silently becomes
                         int32 and ms timestamps truncate.

Traced-value analysis is intraprocedural with same-module call-graph
propagation: parameters of functions bound by module-scope `jax.jit`
(minus static_argnums/static_argnames) seed the traced set; a call from
a traced function propagates traced-rooted arguments into the callee's
parameters to fixpoint.  Expressions reached only through `.shape` /
`.dtype` / `.ndim` / `len()` / `isinstance()` are static at trace time
and never count as traced-rooted.
"""

from __future__ import annotations

import ast
import os

from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_HOST_SYNC = "jax-host-sync"
RULE_TRACER_BRANCH = "jax-tracer-branch"
RULE_JIT_PER_CALL = "jax-jit-per-call"
RULE_INT64_GUARD = "jax-int64-no-x64-guard"

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr"}
_SYNC_METHODS = {"item", "tolist"}
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_NP_SYNC_FUNCS = {"asarray", "array", "frombuffer", "copy"}


def _imports_jax(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax"
                                or node.module.startswith("jax.")):
                return True
    return False


def _is_jax_jit(node: ast.expr) -> bool:
    """`jax.jit` as an expression (also bare `jit` imported from jax)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_target(call: ast.Call):
    """(func_expr, static_positions, static_names) for a jax.jit(...) or
    partial(jax.jit, ...) call; None when `call` is neither."""
    if _is_jax_jit(call.func):
        target = call.args[0] if call.args else None
    elif (isinstance(call.func, (ast.Name, ast.Attribute))
          and (getattr(call.func, "id", None) == "partial"
               or getattr(call.func, "attr", None) == "partial")
          and call.args and _is_jax_jit(call.args[0])):
        target = call.args[1] if len(call.args) > 1 else None
    else:
        return None
    positions: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            positions.update(_int_tuple(kw.value))
        elif kw.arg == "static_argnames":
            names.update(_str_tuple(kw.value))
    return target, positions, names


def _int_tuple(node: ast.expr) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _str_tuple(node: ast.expr) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _param_names(fn: ast.FunctionDef) -> list[str]:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


class _TracedRooted(ast.NodeVisitor):
    """Does an expression reach a traced name other than through a
    static (.shape/.dtype/len/...) window?"""

    def __init__(self, traced: set[str]):
        self.traced = traced
        self.hit = False

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.traced:
            self.hit = True

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _STATIC_ATTRS:
            return
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id in _STATIC_CALLS:
            return
        if isinstance(node.func, ast.Attribute):
            self.visit(node.func.value)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_Compare(self, node: ast.Compare) -> None:
        # `"key" in wargs` — dict membership on a traced-values dict is
        # resolved at trace time; a constant left operand marks it.
        if (len(node.ops) == 1 and isinstance(node.ops[0],
                                              (ast.In, ast.NotIn))
                and isinstance(node.left, ast.Constant)):
            return
        self.generic_visit(node)


def _rooted(expr: ast.expr, traced: set[str]) -> bool:
    if not traced:
        return False
    v = _TracedRooted(traced)
    v.visit(expr)
    return v.hit


def _module_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}


def _seed_traced(tree: ast.Module, funcs: dict[str, ast.FunctionDef]
                 ) -> dict[str, set[str]]:
    """Traced params of functions jit-bound at module scope."""
    traced: dict[str, set[str]] = {}

    def bind(target: ast.expr, positions: set[int], names: set[str]) -> None:
        if not isinstance(target, ast.Name) or target.id not in funcs:
            return
        fn = funcs[target.id]
        params = _param_names(fn)
        static = {params[i] for i in positions if i < len(params)} | names
        traced.setdefault(fn.name, set()).update(
            p for p in params if p not in static)

    for node in tree.body:
        for call in ast.walk(node):
            if isinstance(call, ast.Call):
                hit = _jit_call_target(call)
                if hit and hit[0] is not None:
                    bind(*hit)
    for name, fn in funcs.items():
        for dec in fn.decorator_list:
            if _is_jax_jit(dec):
                traced.setdefault(name, set()).update(_param_names(fn))
            elif isinstance(dec, ast.Call):
                hit = _jit_call_target(dec)
                if hit is not None:
                    _, positions, names2 = hit
                    params = _param_names(fn)
                    static = {params[i] for i in positions
                              if i < len(params)} | names2
                    traced.setdefault(name, set()).update(
                        p for p in params if p not in static)
    return traced


def _propagate(funcs: dict[str, ast.FunctionDef],
               traced: dict[str, set[str]]) -> None:
    """Same-module fixpoint: traced-rooted call args taint callee params."""
    changed = True
    while changed:
        changed = False
        for name, tset in list(traced.items()):
            fn = funcs.get(name)
            if fn is None or not tset:
                continue
            for call in ast.walk(fn):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)
                        and call.func.id in funcs):
                    continue
                callee = funcs[call.func.id]
                params = _param_names(callee)
                tgt = traced.setdefault(callee.name, set())
                for i, arg in enumerate(call.args):
                    if i < len(params) and params[i] not in tgt \
                            and _rooted(arg, tset):
                        tgt.add(params[i])
                        changed = True
                for kw in call.keywords:
                    if kw.arg in params and kw.arg not in tgt \
                            and _rooted(kw.value, tset):
                        tgt.add(kw.arg)
                        changed = True


def _uses_jnp_int64(tree: ast.Module) -> int:
    """First line using jnp.int64 / jax.numpy.int64, or 0."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == "int64":
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("jnp",):
                return node.lineno
            if isinstance(base, ast.Attribute) and base.attr == "numpy" \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "jax":
                return node.lineno
    return 0


def _has_x64_guard(src: SourceFile) -> bool:
    """The module itself, a package __init__ above it, or an import of
    the ops package (whose __init__ pins x64 process-wide) guards x64."""
    if "jax_enable_x64" in src.text or "x64" in _identifiers(src.tree):
        return True
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith("opentsdb_tpu.ops"):
            return True
        if isinstance(node, ast.Import) and any(
                a.name.startswith("opentsdb_tpu.ops") for a in node.names):
            return True
    d = os.path.dirname(src.abspath)
    for _ in range(6):
        init = os.path.join(d, "__init__.py")
        if os.path.isfile(init):
            try:
                with open(init, "r", encoding="utf-8") as fh:
                    if "jax_enable_x64" in fh.read():
                        return True
            except OSError:
                pass
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return False


def _identifiers(tree: ast.Module) -> str:
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
        elif isinstance(node, ast.alias):
            names.append(node.asname or node.name)
        elif isinstance(node, ast.FunctionDef):
            names.append(node.name)
    return " ".join(names)


def _is_memoizer(dec: ast.expr) -> bool:
    """@lru_cache / @cache / @functools.lru_cache(...) decorators."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    name = dec.attr if isinstance(dec, ast.Attribute) else \
        dec.id if isinstance(dec, ast.Name) else ""
    return name in ("lru_cache", "cache")


def _jit_per_call(src: SourceFile) -> list[Finding]:
    out: list[Finding] = []

    def visit(node: ast.AST, stack: list):
        for child in ast.iter_child_nodes(node):
            frame = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the function's own decorators run at its DEFINITION
                # scope, not inside it
                for dec in child.decorator_list:
                    visit(dec, stack)
                frame = stack + [child]
                for part in child.body:
                    visit(part, frame)
                continue
            if isinstance(child, ast.Call) and stack \
                    and _jit_call_target(child) is not None:
                memoized = any(
                    any(_is_memoizer(d) for d in fn.decorator_list)
                    for fn in stack)
                if not memoized:
                    out.append(Finding(
                        src.path, child.lineno, RULE_JIT_PER_CALL,
                        "jax.jit constructed inside '%s': per-call jit "
                        "wrappers retrace every invocation — hoist to "
                        "module scope, or memoize (@lru_cache, or a dict "
                        "cache + suppression comment)" % stack[-1].name))
            visit(child, frame)

    visit(src.tree, [])
    return out


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    if not _imports_jax(src.tree):
        return []
    out: list[Finding] = []
    funcs = _module_functions(src.tree)
    traced = _seed_traced(src.tree, funcs)
    _propagate(funcs, traced)

    int64_line = _uses_jnp_int64(src.tree)
    if int64_line and not _has_x64_guard(src):
        out.append(Finding(
            src.path, int64_line, RULE_INT64_GUARD,
            "jnp.int64 used without an x64 guard: with jax_enable_x64 off "
            "this is silently int32 and ms timestamps truncate — enable "
            "x64 in the package __init__ or add an explicit guard"))

    # jit construction inside any function body (module scope is the
    # cheap, compile-once place for it).  Memoized builders — functions
    # under @lru_cache/@cache — construct once per static key and are
    # exempt; hand-rolled dict caches suppress with a comment.
    out.extend(_jit_per_call(src))

    for name, tset in traced.items():
        fn = funcs.get(name)
        if fn is None or not tset:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and _rooted(node.test, tset):
                out.append(Finding(
                    src.path, node.lineno, RULE_TRACER_BRANCH,
                    "Python branch on a traced value in jit-reachable "
                    "'%s': use jnp.where / lax.cond instead" % name))
            elif isinstance(node, ast.IfExp) and _rooted(node.test, tset):
                out.append(Finding(
                    src.path, node.lineno, RULE_TRACER_BRANCH,
                    "conditional expression on a traced value in "
                    "jit-reachable '%s': use jnp.where / lax.cond "
                    "instead" % name))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _SYNC_METHODS \
                        and _rooted(f.value, tset):
                    out.append(Finding(
                        src.path, node.lineno, RULE_HOST_SYNC,
                        ".%s() on a traced value in jit-reachable '%s' "
                        "forces a device sync" % (f.attr, name)))
                elif isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS \
                        and node.args and _rooted(node.args[0], tset):
                    out.append(Finding(
                        src.path, node.lineno, RULE_HOST_SYNC,
                        "%s() on a traced value in jit-reachable '%s' "
                        "forces a device sync" % (f.id, name)))
                elif isinstance(f, ast.Attribute) \
                        and f.attr in _NP_SYNC_FUNCS \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in ("np", "numpy") \
                        and node.args and _rooted(node.args[0], tset):
                    out.append(Finding(
                        src.path, node.lineno, RULE_HOST_SYNC,
                        "np.%s() on a traced value in jit-reachable '%s' "
                        "pulls the array to the host" % (f.attr, name)))
    return out


ANALYZER = Analyzer(
    "jax_hygiene",
    (RULE_HOST_SYNC, RULE_TRACER_BRANCH, RULE_JIT_PER_CALL,
     RULE_INT64_GUARD),
    check)
