"""Lock discipline: guarded-by annotations, unguarded mutations, cycles.

The threaded TSD server grew stateful fault-tolerance (per-peer
breakers, WAL, drain-on-shutdown); this analyzer makes the locking
contract explicit and machine-checked.  Three rules:

  lock-missing-annotation  a class attribute is mutated inside a
                           `with self.<lock>` block somewhere, so it is
                           shared state — its declaration must carry a
                           `# guarded-by: <lock>` annotation (inline, or
                           a standalone comment covering the contiguous
                           assignment block below it).  Also fired when
                           an annotation names a lock the class doesn't
                           hold.
  lock-unguarded-mutation  a guarded-by-annotated attribute is mutated
                           without the named lock held.  `__init__` and
                           methods named `*_locked` (the caller-holds-
                           the-lock convention) are exempt.
  lock-order-cycle         the graph "while holding (Class, lockA), a
                           call is made that acquires (Class', lockB)"
                           contains a cycle — including the length-1
                           cycle of re-acquiring a non-reentrant Lock on
                           the same instance (self-deadlock).

Mutations tracked: assignment / augmented assignment / deletion of
`self.<attr>`, and subscript stores into `self.<attr>[...]`.  In-place
method mutation (`self.x.append(...)`) is out of scope — annotate and
guard the attribute anyway; the write-through rules still catch
rebinding.
"""

from __future__ import annotations

import ast

from tools.lint.annotations import (ClassAnnotations, scan_class_annotations,
                                    self_attr as _self_attr)
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_MISSING = "lock-missing-annotation"
RULE_UNGUARDED = "lock-unguarded-mutation"
RULE_CYCLE = "lock-order-cycle"


def _mutation_targets(stmt: ast.stmt) -> list[str]:
    """self-attributes this statement mutates."""
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    out: list[str] = []
    for t in targets:
        if isinstance(t, ast.Tuple):
            out.extend(a for e in t.elts
                       if (a := _self_attr(e)) is not None)
            continue
        attr = _self_attr(t)
        if attr is not None:
            out.append(attr)
            continue
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                out.append(attr)
    return out


class _ClassInfo(ClassAnnotations):
    """ClassAnnotations (the shared grammar: locks, guarded-by, decl
    lines, attr types — tools/lint/annotations.py) plus the
    static-analysis-only state: mutation sites and the under-lock call
    graph."""

    def __init__(self, name: str, path: str, lineno: int):
        super().__init__(name, path, lineno)
        # (attr, method, line, frozenset(held locks))
        self.mutations: list[tuple[str, str, int, frozenset]] = []
        # method -> set of lock attrs it acquires (with self.X)
        self.acquires: dict[str, set[str]] = {}
        # (held lock, call node, method) for the cycle graph
        self.calls_under_lock: list[tuple[str, ast.Call, str]] = []


def _scan_class(src: SourceFile, cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(cls.name, src.path, cls.lineno)
    # passes 1 + 2 (lock attrs, declarations, guarded-by annotations)
    # are the shared grammar
    scan_class_annotations(src.lines, cls, src.path, into=info)
    # pass 3: mutations + lock acquisition + calls under lock
    for m in cls.body:
        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _walk_with_locks(m, m.body, frozenset(), info)
    return info


def _walk_with_locks(method: ast.FunctionDef, body: list[ast.stmt],
                     held: frozenset, info: _ClassInfo) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in stmt.items:
                expr = item.context_expr
                # `with self._lock:` / `with self._lock.acquire...` no —
                # plain attribute context managers only
                attr = _self_attr(expr)
                if attr is not None and attr in info.locks:
                    acquired.add(attr)
            now = held | acquired
            for a in acquired:
                info.acquires.setdefault(method.name, set()).add(a)
            _walk_with_locks(method, stmt.body, frozenset(now), info)
            continue
        for attr in _mutation_targets(stmt):
            info.mutations.append((attr, method.name, stmt.lineno, held))
        # nested statements (if/for/try/...) — recurse into their bodies
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _walk_with_locks(method, sub, held, info)
        for handler in getattr(stmt, "handlers", []) or []:
            _walk_with_locks(method, handler.body, held, info)
        if held:
            for node in ast.walk(stmt) if not isinstance(
                    stmt, (ast.With, ast.AsyncWith)) else []:
                if isinstance(node, ast.Call):
                    for lock in held:
                        info.calls_under_lock.append(
                            (lock, node, method.name))


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    out: list[Finding] = []
    bucket = ctx.bucket("lock")
    classes = bucket.setdefault("classes", {})
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = _scan_class(src, node)
        classes[info.name] = info
        if not info.locks:
            continue
        # attrs mutated under a lock (outside __init__) are shared state
        shared: dict[str, set[str]] = {}
        for attr, method, _line, held in info.mutations:
            if method == "__init__" or attr in info.locks:
                continue
            if held:
                shared.setdefault(attr, set()).update(held)
        for attr, locks in sorted(shared.items()):
            if attr not in info.annotations:
                named = ", ".join("'%s'" % n for n in sorted(locks))
                out.append(Finding(
                    src.path, info.init_lines.get(attr, info.lineno),
                    RULE_MISSING,
                    "%s.%s is mutated under lock %s but its declaration "
                    "has no '# guarded-by: <lock>' annotation"
                    % (info.name, attr, named)))
        for attr, (lock, line) in sorted(info.annotations.items()):
            if lock not in info.locks:
                out.append(Finding(
                    src.path, line, RULE_MISSING,
                    "%s.%s is annotated guarded-by '%s' but the class "
                    "holds no such lock" % (info.name, attr, lock)))
                continue
            for mattr, method, mline, held in info.mutations:
                if mattr != attr or method == "__init__" \
                        or method.endswith("_locked"):
                    continue
                if lock not in held:
                    out.append(Finding(
                        src.path, mline, RULE_UNGUARDED,
                        "%s.%s (guarded-by %s) is mutated in '%s' without "
                        "the lock held" % (info.name, attr, lock, method)))
    return out


def _cycle_edges(classes: dict[str, _ClassInfo]):
    """(holder_node, target_node, path, line) edges between (Class, lock)
    nodes, resolved through self-calls and typed attribute calls."""
    edges = []
    for info in classes.values():
        for lock, call, method in info.calls_under_lock:
            f = call.func
            if not isinstance(f, ast.Attribute):
                continue
            target: _ClassInfo | None = None
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                target = info
            else:
                attr = _self_attr(f.value)
                if attr is not None:
                    tname = info.attr_types.get(attr)
                    target = classes.get(tname) if tname else None
            if target is None:
                continue
            for tlock in sorted(target.acquires.get(f.attr, ())):
                src_node = (info.name, lock)
                dst_node = (target.name, tlock)
                if src_node == dst_node and \
                        info.locks.get(lock) == "RLock":
                    continue    # reentrant: same-lock self-call is fine
                edges.append((src_node, dst_node, info.path, call.lineno))
    return edges


def finish(ctx: LintContext) -> list[Finding]:
    classes = ctx.bucket("lock").get("classes", {})
    edges = _cycle_edges(classes)
    graph: dict[tuple, set[tuple]] = {}
    meta: dict[tuple[tuple, tuple], tuple[str, int]] = {}
    for a, b, path, line in edges:
        graph.setdefault(a, set()).add(b)
        meta.setdefault((a, b), (path, line))
    out: list[Finding] = []
    seen_cycles: set[tuple] = set()
    for start in sorted(graph):
        stack = [(start, (start,))]
        while stack:
            node, path_nodes = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cycle = path_nodes + (start,)
                    # canonical rotation for dedup
                    body = cycle[:-1]
                    k = min(range(len(body)),
                            key=lambda i: body[i:] + body[:i])
                    canon = body[k:] + body[:k]
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    fpath, fline = meta[(node, start)]
                    out.append(Finding(
                        fpath, fline, RULE_CYCLE,
                        "lock-order cycle: " + " -> ".join(
                            "%s.%s" % n for n in cycle)))
                elif nxt not in path_nodes:
                    stack.append((nxt, path_nodes + (nxt,)))
    return out


def static_order_edges(root: str | None = None,
                       paths: tuple[str, ...] = ("opentsdb_tpu",)
                       ) -> set[tuple[tuple[str, str], tuple[str, str]]]:
    """The statically-derived lock-order graph over `paths`:
    ((HolderClass, held_lock), (TargetClass, acquired_lock)) edges —
    the node space tsdbsan's deadlock watcher cross-checks its observed
    runtime graph against (tools/sanitize/deadlock.py)."""
    from tools.lint.core import REPO_ROOT, LintContext, run_lint
    ctx = LintContext(root or REPO_ROOT)
    run_lint(paths, root=root or REPO_ROOT, analyzers=[ANALYZER], ctx=ctx)
    classes = ctx.bucket("lock").get("classes", {})
    return {(a, b) for a, b, _path, _line in _cycle_edges(classes)}


ANALYZER = Analyzer(
    "lock_discipline", (RULE_MISSING, RULE_UNGUARDED, RULE_CYCLE),
    check, finish)
