"""Metrics-name schema checks: every emitted metric must be declared.

`opentsdb_tpu/obs/__init__.py` declares `METRICS_SCHEMA` (name ->
kind, labels, doc).  This analyzer holds every emission site to it —
the per-metric mirror of config_schema's key discipline.  Ad-hoc
metric names rot silently: a typo'd counter scrapes as a NEW series
forever, a gauge re-registered as a counter 500s the stats endpoint at
runtime, and a dashboard built on an undeclared name breaks the day
someone "cleans it up".

Emission sites checked:

  * `REGISTRY.counter/gauge/histogram("name", ...)` — the pull-style
    obs/registry.py families (the call's attribute IS the kind).
  * `collector.record("name", ...)` — StatsCollector push records,
    exposed as gauges on /api/stats/prometheus; the declared name is
    the full dotted form WITH the collector's "tsd." prefix.

Name resolution: a string literal matches exactly; a %-formatted
template ("%s.errors" % kind) matches with each hole as a `*` segment
("tsd.*.errors" must be declared verbatim); anything else is a dynamic
name (see below).

Rules:

  metrics-unknown-name    the (wildcarded) name is not declared in
                          METRICS_SCHEMA
  metrics-kind-collision  the emission kind disagrees with the schema
                          (a record() against a name declared counter/
                          histogram, or REGISTRY.gauge on a declared
                          counter — the registry raises on this at
                          runtime; catch it before it ships)
  metrics-dynamic-name    the name is computed (variable, f-string
                          with no literal backbone) — unverifiable
                          statically.  Generic forwarders that re-emit
                          names already walked from collect_stats()
                          suppress this with a justification comment.
  metrics-unknown-label   a `.labels(k=...)` chained on the family
                          call, or a literal `"k=v"` xtratag, uses a
                          label key the schema does not declare
"""

from __future__ import annotations

import ast

from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_UNKNOWN = "metrics-unknown-name"
RULE_KIND = "metrics-kind-collision"
RULE_DYNAMIC = "metrics-dynamic-name"
RULE_LABEL = "metrics-unknown-label"

FAMILY_KINDS = ("counter", "gauge", "histogram")
RECORD_RECEIVERS = frozenset({"collector", "stats_collector"})
RECORD_PREFIX = "tsd."


def _load_schema(ctx: LintContext) -> dict:
    """name -> (kind, labels).  Tests inject via
    ctx.bucket("metrics")["schema"]."""
    bucket = ctx.bucket("metrics")
    if "schema" not in bucket:
        from opentsdb_tpu.obs import METRICS_SCHEMA
        bucket["schema"] = {k: (s.kind, tuple(s.labels))
                            for k, s in METRICS_SCHEMA.items()}
    return bucket["schema"]


def _template_name(node: ast.expr) -> str | None:
    """Literal name, or a %-format/f-string template with `*` holes;
    None when the name is fully dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        out = node.left.value
        for hole in ("%s", "%d", "%r"):
            out = out.replace(hole, "*")
        return out
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        out = "".join(parts)
        return out if out.strip("*") else None
    return None


def _family_call(node: ast.Call) -> str | None:
    """'counter'/'gauge'/'histogram' when node is REGISTRY.<kind>(...)."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in FAMILY_KINDS and \
            isinstance(f.value, ast.Name) and f.value.id == "REGISTRY":
        return f.attr
    return None


def _record_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "record"
            and isinstance(f.value, ast.Name)
            and f.value.id in RECORD_RECEIVERS)


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    schema = _load_schema(ctx)
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _family_call(node)
        if kind is not None and node.args:
            name = _template_name(node.args[0])
            if name is None:
                out.append(Finding(
                    src.path, node.lineno, RULE_DYNAMIC,
                    "REGISTRY.%s() with a computed metric name — "
                    "declare the name in METRICS_SCHEMA and emit a "
                    "literal (or template), or suppress with a "
                    "justification at a sanctioned forwarder" % kind))
                continue
            decl = schema.get(name)
            if decl is None:
                out.append(Finding(
                    src.path, node.lineno, RULE_UNKNOWN,
                    "metric '%s' (via REGISTRY.%s) is not declared in "
                    "METRICS_SCHEMA" % (name, kind)))
            elif decl[0] != kind:
                out.append(Finding(
                    src.path, node.lineno, RULE_KIND,
                    "REGISTRY.%s() on metric '%s' which METRICS_SCHEMA "
                    "declares a %s — the registry raises on this kind "
                    "collision at runtime" % (kind, name, decl[0])))
            continue
        # chained .labels(k=...) on a family call
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "labels" and \
                isinstance(f.value, ast.Call):
            fam_kind = _family_call(f.value)
            if fam_kind is not None and f.value.args:
                name = _template_name(f.value.args[0])
                decl = schema.get(name) if name else None
                if decl is not None:
                    for kw in node.keywords:
                        if kw.arg is not None and \
                                kw.arg not in decl[1]:
                            out.append(Finding(
                                src.path, node.lineno, RULE_LABEL,
                                "label '%s' on metric '%s' is not in "
                                "its declared label set %r"
                                % (kw.arg, name, list(decl[1]))))
            continue
        if _record_call(node) and node.args:
            name = _template_name(node.args[0])
            if name is None:
                out.append(Finding(
                    src.path, node.lineno, RULE_DYNAMIC,
                    "collector.record() with a computed metric name — "
                    "declare the name in METRICS_SCHEMA and emit a "
                    "literal (or template), or suppress with a "
                    "justification at a sanctioned forwarder"))
                continue
            full = RECORD_PREFIX + name
            decl = schema.get(full)
            if decl is None:
                out.append(Finding(
                    src.path, node.lineno, RULE_UNKNOWN,
                    "metric '%s' (via collector.record) is not "
                    "declared in METRICS_SCHEMA" % full))
                continue
            if decl[0] != "gauge":
                out.append(Finding(
                    src.path, node.lineno, RULE_KIND,
                    "collector.record() on metric '%s' which "
                    "METRICS_SCHEMA declares a %s — records expose as "
                    "gauges on /api/stats/prometheus" % (full, decl[0])))
            if len(node.args) >= 3:
                key = _xtratag_key(node.args[2])
                if key is not None and key not in decl[1]:
                    out.append(Finding(
                        src.path, node.lineno, RULE_LABEL,
                        "xtratag key '%s' on metric '%s' is not in its "
                        "declared label set %r"
                        % (key, full, list(decl[1]))))
    return out


def _xtratag_key(node: ast.expr) -> str | None:
    """The tag key of a literal/templated "k=v" xtratag argument."""
    text = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) \
            and isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        text = node.left.value
    if text and "=" in text:
        key = text.split("=", 1)[0]
        if key and "%" not in key:
            return key
    return None


ANALYZER = Analyzer(
    "metrics_schema", (RULE_UNKNOWN, RULE_KIND, RULE_DYNAMIC, RULE_LABEL),
    check)
