"""Ordering & failure-atomicity contracts — happens-before and
rollback-on-raise verified over the PR 3 call graph.

The bug class hand-review kept catching — PR 9's mark-before-write
stale serve, PR 7's leaked log handler, PR 15's ship-before-ack
invariant — is a happens-before or failure-atomicity violation on
shared state.  Two analyzers make those orderings checked contracts:

  order_contract
    order-violation      a declared happens-before contract
                         (`# order: <a> before <b>`, grammar shared
                         with tsdbsan in tools/lint/annotations.py) is
                         violated: some function that sequences both
                         events has a path reaching a `<b>` site
                         (`# order-event: <b>`) with `<a>` still
                         undischarged.

  failure_atomicity
    atomicity-torn-on-raise   a multi-write guarded-state transition
                         (>= 2 writes to `# guarded-by:` attrs inside
                         one `with self.<lock>:` region, or a declared
                         `# atomic:` group) interleaves a fallible
                         call between its first and last write with no
                         rollback on the raising path (try/except or
                         finally that restores the involved state).
    install-leak-on-raise    a `# global-install` site armed in
                         `__init__` before later fallible construction
                         work, with no rollback on the failing path —
                         generalizes the PR 7 hand-hardening of
                         `TSDServer.__init__` into a rule.

order_contract semantics (resource_leak-style statement walk):

  * An `# order-event:` tag attaches to the statement on its line (or
    the line below a standalone comment).  On a `with` statement the
    event fires at block EXIT (permit released when the context
    closes).
  * Event emission is transitive: a statement emits every event its
    (uniquely resolved) callees emit, to a fixpoint over the call
    graph.  Resolution is stricter than blocking's — only unambiguous
    targets (self-methods, typed attributes, unique names) create
    edges, so a 4-way devirtualization blob can neither invent nor
    launder an ordering.
  * A function is verified for contract (a, b) only when it actually
    SEQUENCES the two events: it has at least one statement emitting
    `a` without `b` and one emitting `b` without `a`.  A statement
    emitting both delegates the ordering to its callee (verified
    there) and discharges `a` — the single-entry-point routing shape.
  * The walk is optimistic: `if` joins union the branches' discharged
    sets, `try` bodies/handlers/finally share one evolving set, and
    the walk continues past `return` (a dead-code reorder still
    reports).

failure_atomicity semantics (segment-local statement scan):

  * Writes pair only within one nesting level — two writes in opposite
    if/else branches can never interleave on a real path, so each
    conditionally-entered block is checked as its own segment and
    exposes only its fallible CALLS upward (a raise inside a branch
    does escape into the enclosing flow).  `with` bodies and
    unprotected `try` bodies are transparent; a protected try (handler
    or finally restores the involved state) discharges interior raises
    and propagates only its surviving writes.  return/break/continue
    are barriers; `raise` is a fallible event then a barrier.
  * Fallibility is a whitelist complement: builtins over well-typed
    operands, plumbing constructors, dict.pop-with-default, metrics
    accessors (labels/inc/observe) and injected clocks are infallible;
    every other call could raise and tear the transition.
  * install-leak protection is judged at the CALL site: a fallible
    call inside a try whose handler rolls back and re-raises cannot
    leak the install, no matter where it was armed.

Seeded contracts (the repo's real load-bearing orderings):

    memstore-write  before memstore-mark       (storage/memstore.py)
    wal-append      before replica-ship        (core/tsdb.py)
    wal-append      before ingest-ack          (tsd/rpcs.py)
    replica-ship    before ingest-ack          (tsd/rpcs.py)
    catch-up-pull   before rejoin-ready        (tsd/replication.py)
    response-write  before permit-release      (tsd/rpcs.py)
    wal-close       before flightrec-shutdown  (core/tsdb.py shutdown)
    spill-close     before flightrec-shutdown  (core/tsdb.py shutdown)
    epoch-bump      before jit-cache-splice    (ops/downsample.py)

Suppressions, SARIF, baseline and --changed-only all inherit from the
runner; fixture/test scopes override the analyzed directories through
`ctx.bucket("ordering")["paths"]`.  `static_order_table()` exports the
contract + event tables tsdbsan's runtime order recorder cross-checks
against (tools/sanitize/order.py), mirroring `static_request_paths`.
"""

from __future__ import annotations

import ast

from tools.lint.annotations import (ClassAnnotations, atomic_annotation,
                                    install_annotation, order_contracts,
                                    order_events,
                                    self_attr as _self_attr)
from tools.lint.astindex import class_annotations, get_ast_index
from tools.lint.callgraph import get_callgraph, module_name
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_ORDER = "order-violation"
RULE_TORN = "atomicity-torn-on-raise"
RULE_INSTALL_LEAK = "install-leak-on-raise"

ORDERING_DIRS = ("opentsdb_tpu/",)

# --------------------------------------------------------------------- #
# Shared tag helpers                                                    #
# --------------------------------------------------------------------- #


def _tags_for_stmt(lines: list[str], st: ast.stmt) -> list[str]:
    """`# order-event:` names attached to one statement: inline on its
    first line, or on a standalone comment line directly above."""
    line = st.lineno
    if line <= len(lines):
        tags = order_events(lines[line - 1])
        if tags:
            return tags
    if line >= 2:
        above = lines[line - 2].strip()
        if above.startswith("#"):
            return order_events(above)
    return []


def _install_for_stmt(lines: list[str], st: ast.stmt) -> bool:
    """True when the statement carries a `# global-install` annotation
    (inline or standalone comment above)."""
    line = st.lineno
    if line <= len(lines) and install_annotation(lines[line - 1]):
        return True
    if line >= 2:
        above = lines[line - 2].strip()
        if above.startswith("#") and install_annotation(above):
            return True
    return False


# --------------------------------------------------------------------- #
# order_contract                                                        #
# --------------------------------------------------------------------- #


class _OrderAnalysis:
    """Whole-program event-emission fixpoint + per-function walks."""

    def __init__(self, ctx: LintContext):
        bucket = ctx.bucket("ordering")
        self.graph = get_callgraph(ctx)
        self.dirs = tuple(bucket.get("paths", ORDERING_DIRS))
        self.contracts: list[tuple[str, str]] = []
        self.contract_sites: dict[tuple[str, str], tuple[str, int]] = {}
        self.events: set[str] = set()
        self.fns: dict[str, tuple] = {}        # qname -> (fi, src, cls)
        self.fn_emits: dict[str, frozenset] = {}
        self._callee_cache: dict[int, tuple[str, ...]] = {}
        self._classes: dict[tuple[str, str], ClassAnnotations] = {}

    def in_scope(self, path: str) -> bool:
        return path.startswith(self.dirs) or \
            any(d in path for d in self.dirs)

    # -- call resolution (unambiguous targets only) -----------------------

    def _unique_callees(self, call: ast.Call, fi, cls) -> tuple[str, ...]:
        cached = self._callee_cache.get(id(call))
        if cached is not None:
            return cached
        recv_types = None
        f = call.func
        if isinstance(f, ast.Attribute):
            attr = _self_attr(f.value)
            if attr is not None and cls is not None:
                t = cls.attr_types.get(attr)
                if t is not None:
                    recv_types = {t}
        qnames = {info.qname
                  for info, _ctor, _cls in self.graph.resolve(
                      call, fi, recv_types=recv_types)
                  if info is not None and ".<nested>." not in info.qname}
        # an ambiguous devirtualization must neither invent nor launder
        # an ordering — only a single unambiguous target creates an edge
        out = tuple(sorted(qnames)) if len(qnames) == 1 else ()
        self._callee_cache[id(call)] = out
        return out

    # -- emission queries -------------------------------------------------

    def expr_emits(self, expr, fi, cls) -> set[str]:
        out: set[str] = set()
        if expr is None:
            return out
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                for q in self._unique_callees(sub, fi, cls):
                    out |= self.fn_emits.get(q, frozenset())
        return out

    def stmt_emits(self, st: ast.stmt, fi, src: SourceFile,
                   cls) -> frozenset:
        ev = set(_tags_for_stmt(src.lines, st))
        if not isinstance(st, (ast.With, ast.AsyncWith)):
            ev |= self.expr_emits(st, fi, cls)
        return frozenset(ev)

    # -- the pass ---------------------------------------------------------

    def run(self, ctx: LintContext) -> None:
        in_scope = [s for s in ctx.files if self.in_scope(s.path)]
        seen: set[tuple[str, str]] = set()
        for src in in_scope:
            for lineno, line in enumerate(src.lines, start=1):
                for pair in order_contracts(line):
                    if pair not in seen:
                        seen.add(pair)
                        self.contracts.append(pair)
                        self.contract_sites[pair] = (src.path, lineno)
                for name in order_events(line):
                    self.events.add(name)
        self._classes = get_ast_index(ctx).classes
        # collect functions + direct tags + edges
        direct: dict[str, set[str]] = {}
        edges: dict[str, set[str]] = {}
        for src in in_scope:
            mod = self.graph.modules.get(module_name(src.path))
            if mod is None:
                continue
            fns = list(mod.functions.values())
            for methods in mod.classes.values():
                fns.extend(methods.values())
            for fi in fns:
                cls = self._classes.get((src.path, fi.klass)) \
                    if fi.klass else None
                self.fns[fi.qname] = (fi, src, cls)
                tags: set[str] = set()
                outs: set[str] = set()
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.stmt) and node is not fi.node \
                            and not isinstance(node, (ast.FunctionDef,
                                                      ast.AsyncFunctionDef,
                                                      ast.ClassDef)):
                        tags.update(_tags_for_stmt(src.lines, node))
                    if isinstance(node, ast.Call):
                        outs.update(self._unique_callees(node, fi, cls))
                direct[fi.qname] = tags
                edges[fi.qname] = outs
        # emission fixpoint over the call graph (cycles converge: the
        # union only grows and the event alphabet is finite)
        emits = {q: set(t) for q, t in direct.items()}
        changed = True
        while changed:
            changed = False
            for q, outs in edges.items():
                cur = emits[q]
                before = len(cur)
                for callee in outs:
                    cur |= emits.get(callee, set())
                if len(cur) != before:
                    changed = True
        self.fn_emits = {q: frozenset(e) for q, e in emits.items()}

    # -- pairing + verification -------------------------------------------

    def _fn_units(self, fi, src, cls) -> list[frozenset]:
        """Flat statement-level emission sets (pairing pre-pass)."""
        units: list[frozenset] = []

        def visit(stmts):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(st.body)
                    continue
                if isinstance(st, ast.ClassDef):
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    entry: set[str] = set()
                    for item in st.items:
                        entry |= self.expr_emits(item.context_expr, fi, cls)
                    if entry:
                        units.append(frozenset(entry))
                    tags = frozenset(_tags_for_stmt(src.lines, st))
                    if tags:
                        units.append(tags)
                    visit(st.body)
                    continue
                if isinstance(st, ast.If):
                    e = self.expr_emits(st.test, fi, cls)
                    if e:
                        units.append(frozenset(e))
                    visit(st.body)
                    visit(st.orelse)
                    continue
                if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                    ctrl = getattr(st, "test", None)
                    if ctrl is None:
                        ctrl = getattr(st, "iter", None)
                    e = self.expr_emits(ctrl, fi, cls)
                    if e:
                        units.append(frozenset(e))
                    visit(st.body)
                    visit(st.orelse)
                    continue
                if isinstance(st, ast.Try):
                    visit(st.body)
                    for h in st.handlers:
                        visit(h.body)
                    visit(st.orelse)
                    visit(st.finalbody)
                    continue
                e = self.stmt_emits(st, fi, src, cls)
                if e:
                    units.append(e)

        visit(fi.node.body)
        return units

    def verify(self) -> list[Finding]:
        findings: list[Finding] = []
        if not self.contracts:
            return findings
        for qname in sorted(self.fns):
            fi, src, cls = self.fns[qname]
            emitted = self.fn_emits.get(qname, frozenset())
            candidates = [(a, b) for (a, b) in self.contracts
                          if a in emitted and b in emitted]
            if not candidates:
                continue
            units = self._fn_units(fi, src, cls)
            active = [(a, b) for (a, b) in candidates
                      if any(a in u and b not in u for u in units)
                      and any(b in u and a not in u for u in units)]
            if not active:
                continue
            walker = _OrderWalk(self, fi, src, cls, active)
            walker.run()
            for line, (a, b) in walker.violations:
                decl = self.contract_sites.get((a, b))
                related = ((decl[0], decl[1],
                            "contract '%s before %s' declared here"
                            % (a, b)),) if decl else ()
                findings.append(Finding(
                    fi.path, line, RULE_ORDER,
                    "event '%s' can be reached before '%s' in '%s' — "
                    "violates the declared contract '# order: %s before "
                    "%s'; reorder so '%s' is discharged on every path "
                    "that crosses '%s' (or move the '# order-event' "
                    "tags with the code if the invariant moved)"
                    % (b, a, fi.name, a, b, a, b), related=related))
        return findings


class _OrderWalk:
    """Resource_leak-style statement walk of one function: maintain the
    set of discharged events at each program point; a statement emitting
    contract side `b` with side `a` undischarged is a violation."""

    def __init__(self, an: _OrderAnalysis, fi, src: SourceFile, cls,
                 contracts: list[tuple[str, str]]):
        self.an = an
        self.fi = fi
        self.src = src
        self.cls = cls
        self.contracts = contracts
        self.violations: list[tuple[int, tuple[str, str]]] = []
        self._seen: set[tuple[int, tuple[str, str]]] = set()

    def run(self) -> None:
        self._walk(self.fi.node.body, set())

    def _check(self, emits: frozenset, line: int,
               discharged: set) -> None:
        for (a, b) in self.contracts:
            if b in emits and a not in emits and a not in discharged:
                key = (line, (a, b))
                if key not in self._seen:
                    self._seen.add(key)
                    self.violations.append(key)
        discharged |= emits

    def _walk(self, stmts, discharged: set) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure runs later on behalf of this function; walk
                # it with a copy so its discharges stay local
                self._walk(st.body, set(discharged))
                continue
            if isinstance(st, ast.ClassDef):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                entry: set[str] = set()
                for item in st.items:
                    entry |= self.an.expr_emits(item.context_expr,
                                                self.fi, self.cls)
                self._check(frozenset(entry), st.lineno, discharged)
                self._walk(st.body, discharged)
                # the statement's own tag fires at block EXIT
                tags = frozenset(_tags_for_stmt(self.src.lines, st))
                self._check(tags, st.lineno, discharged)
                continue
            if isinstance(st, ast.If):
                self._check(frozenset(self.an.expr_emits(
                    st.test, self.fi, self.cls)), st.lineno, discharged)
                d1 = set(discharged)
                self._walk(st.body, d1)
                d2 = set(discharged)
                self._walk(st.orelse, d2)
                # optimistic join: either branch's discharge counts
                discharged |= d1 | d2
                continue
            if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                ctrl = getattr(st, "test", None)
                if ctrl is None:
                    ctrl = getattr(st, "iter", None)
                self._check(frozenset(self.an.expr_emits(
                    ctrl, self.fi, self.cls)), st.lineno, discharged)
                self._walk(st.body, discharged)
                self._walk(st.orelse, discharged)
                continue
            if isinstance(st, ast.Try):
                self._walk(st.body, discharged)
                for h in st.handlers:
                    self._walk(h.body, discharged)
                self._walk(st.orelse, discharged)
                self._walk(st.finalbody, discharged)
                continue
            emits = self.an.stmt_emits(st, self.fi, self.src, self.cls)
            self._check(emits, st.lineno, discharged)


# --------------------------------------------------------------------- #
# failure_atomicity                                                     #
# --------------------------------------------------------------------- #

# Calls that cannot raise under the repo's idioms: builtins over
# well-typed operands, the threading/collections constructors the tree
# uses for plumbing, and side-effect-free accessors.  Everything else
# is treated as fallible — the analyzer asks "could a raise here tear
# the transition", and the answer for an arbitrary call is yes.
_INFALLIBLE_FUNCS = frozenset({
    "len", "int", "float", "str", "bool", "bytes", "abs", "round", "min",
    "max", "sum", "sorted", "all", "any", "id", "repr", "hash",
    "isinstance", "issubclass", "hasattr", "getattr", "tuple", "list",
    "dict", "set", "frozenset", "enumerate", "zip", "range", "iter",
    "print", "format", "type", "callable", "vars", "object",
})
_INFALLIBLE_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "deque", "defaultdict", "OrderedDict",
    "Counter", "Random", "WeakSet", "WeakValueDictionary",
})
_INFALLIBLE_METHODS = frozenset({
    "get", "items", "keys", "values", "copy", "append", "appendleft",
    "extend", "add", "discard", "clear", "setdefault", "update",
    "monotonic", "perf_counter", "time", "locked", "strip", "lstrip",
    "rstrip", "split", "join", "startswith", "endswith", "lower",
    "upper", "replace", "encode", "decode", "release", "notify",
    "notify_all",
    # numpy reductions over well-typed arrays
    "all", "any",
    # metrics plumbing: prometheus-style registries never raise from
    # labels()/inc()/observe(), and treating instrumentation as a
    # fallibility boundary would demand try/except around every gauge
    "labels", "inc", "dec", "observe",
    # injected clock callables (the repo's convention for testable time)
    "_clock",
})


def _fallible_label(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _INFALLIBLE_FUNCS or f.id in _INFALLIBLE_CTORS:
            return None
        return f.id
    if isinstance(f, ast.Attribute):
        if f.attr in _INFALLIBLE_METHODS or f.attr in _INFALLIBLE_CTORS:
            return None
        if f.attr == "pop" and len(call.args) + len(call.keywords) >= 2:
            # dict.pop(key, default) cannot raise; one-arg pop can
            return None
        return f.attr
    return "call"


def _calls_in(expr):
    """Calls in one expression, excluding lambda/comprehension-deferred
    bodies is overkill for this tree — but lambdas genuinely defer, so
    their bodies are skipped."""
    if expr is None:
        return
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _write_targets(st) -> list[str]:
    """self-attribute names written by one assignment statement
    (`self.a = ...`, `self.a[k] = ...`, `self.a += ...`, tuples)."""
    if isinstance(st, ast.Assign):
        targets = list(st.targets)
    elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
        targets = [st.target]
    else:
        return []
    out: list[str] = []
    queue = list(targets)
    while queue:
        t = queue.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            queue.extend(t.elts)
            continue
        if isinstance(t, ast.Subscript):
            t = t.value
        attr = _self_attr(t)
        if attr is not None:
            out.append(attr)
    return out


def _writes_any(stmts, attrs: set) -> bool:
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                if any(a in attrs for a in _write_targets(node)):
                    return True
    return False


def _has_call(stmts) -> bool:
    return any(isinstance(n, ast.Call)
               for st in stmts for n in ast.walk(st))


def _try_restores(tr: ast.Try, attrs: set) -> bool:
    """A try whose handler or finally visibly restores the involved
    state (writes one of the attrs, or runs a rollback call) protects
    the transition — optimistic, like every join in this suite."""
    for h in tr.handlers:
        if _writes_any(h.body, attrs) or _has_call(h.body):
            return True
    if tr.finalbody and (_writes_any(tr.finalbody, attrs)
                         or _has_call(tr.finalbody)):
        return True
    return False


_BARRIER = ("barrier", 0, None)


def _torn_findings(events: list[tuple], attrs_label: str, fn_name: str,
                   path: str) -> list[Finding]:
    write_idx = [i for i, e in enumerate(events) if e[0] == "write"]
    if len({events[i][2] for i in write_idx}) < 2:
        return []
    first, last = write_idx[0], write_idx[-1]
    for i in range(first + 1, last):
        if events[i][0] == "call":
            involved = sorted({events[j][2] for j in write_idx})
            return [Finding(
                path, events[i][1], RULE_TORN,
                "transition over %s ('%s', %s) interleaves fallible "
                "'%s' between its writes — a raise there leaves the "
                "state half-applied; finish the writes before the "
                "call, hoist it out of the transition, or roll back "
                "in try/except-finally"
                % (attrs_label, "', '".join(involved), fn_name,
                   events[i][2]))]
    return []


def _segment_findings(stmts, attrs: set, attrs_label: str, fn_name: str,
                      path: str) -> list[Finding]:
    """Torn-transition findings for one region, segment-locally.

    Writes pair only with writes at the SAME nesting level: two writes
    in different branches of an if/else can never interleave on a real
    path, so a conditionally-entered block is checked as its own
    segment and exposes only its fallible CALLS to the enclosing flow
    (a raise inside the branch does escape, so it still interleaves the
    parent's writes).  `with` bodies and unprotected `try` bodies
    execute in the enclosing flow and are transparent.  A protected try
    (handler/finally restores the involved state) discharges interior
    raises: its surviving writes propagate, its calls do not.  return/
    break/continue are barriers — events on the two sides of one cannot
    interleave; `raise` is a fallible event followed by a barrier.
    """
    findings: list[Finding] = []

    def emit(evs):
        chunk: list[tuple] = []
        for e in evs + [_BARRIER]:
            if e[0] == "barrier":
                findings.extend(_torn_findings(
                    chunk, attrs_label, fn_name, path))
                chunk = []
            else:
                chunk.append(e)

    def check(body, checked=True):
        """Check a conditionally-entered block as its own segment;
        expose only its fallible calls to the enclosing flow.
        ``checked=False`` (inside a protected try) collects without
        reporting — interior raises are rolled back by the handler."""
        evs = collect(body, checked)
        if checked:
            emit(evs)
        return [e for e in evs if e[0] == "call"]

    def collect(body, checked=True):
        evs: list[tuple] = []

        def calls_of(expr):
            for c in _calls_in(expr):
                label = _fallible_label(c)
                if label is not None:
                    evs.append(("call", c.lineno, label))

        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Try):
                if _try_restores(st, attrs):
                    # raises inside are rolled back, so interior
                    # interleavings are discharged; writes that survive
                    # (the body completed) still pair with the
                    # enclosing flow's writes
                    for part in (st.body, st.orelse, st.finalbody):
                        evs.extend(e for e in collect(part, False)
                                   if e[0] == "write")
                    continue
                evs.extend(collect(st.body, checked))
                for h in st.handlers:
                    evs.extend(check(h.body, checked))
                evs.extend(collect(st.orelse, checked))
                evs.extend(collect(st.finalbody, checked))
                continue
            if isinstance(st, ast.If):
                calls_of(st.test)
                evs.extend(check(st.body, checked))
                evs.extend(check(st.orelse, checked))
                continue
            if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                calls_of(getattr(st, "test", None) or
                         getattr(st, "iter", None))
                evs.extend(check(st.body, checked))
                evs.extend(check(st.orelse, checked))
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    calls_of(item.context_expr)
                evs.extend(collect(st.body, checked))
                continue
            if isinstance(st, (ast.Return, ast.Break, ast.Continue)):
                calls_of(getattr(st, "value", None))
                evs.append(_BARRIER)
                continue
            if isinstance(st, ast.Raise):
                calls_of(st.exc)
                evs.append(("call", st.lineno, "raise"))
                evs.append(_BARRIER)
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                calls_of(getattr(st, "value", None))
                for attr in _write_targets(st):
                    if attr in attrs:
                        evs.append(("write", st.lineno, attr))
                continue
            calls_of(st)
        return evs

    emit(collect(stmts))
    return findings


def _method_lock_regions(m, cls: ClassAnnotations):
    """(lock attr, body stmts) for each `with self.<lock>:` region."""
    for node in ast.walk(m):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in cls.locks:
                yield attr, node.body
                break


def _check_atomicity(src: SourceFile, ctx: LintContext) -> list[Finding]:
    dirs = tuple(ctx.bucket("ordering").get("paths", ORDERING_DIRS))
    if not (src.path.startswith(dirs) or any(d in src.path for d in dirs)):
        return []
    findings: list[Finding] = []
    per_file = class_annotations(ctx, src)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = per_file[node.name]
        groups: dict[str, set] = {}
        for attr, line in cls.init_lines.items():
            g = atomic_annotation(src.lines[line - 1]) if \
                line <= len(src.lines) else None
            if g is None and line >= 2:
                above = src.lines[line - 2].strip()
                if above.startswith("#"):
                    g = atomic_annotation(above)
            if g is not None:
                groups.setdefault(g, set()).add(attr)
        for m in node.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if m.name != "__init__":
                # lock regions: >= 2 guarded attrs written in one
                for lock, body in _method_lock_regions(m, cls):
                    attrs = {a for a, lk in cls.guarded.items()
                             if lk == lock}
                    if len(attrs) < 2:
                        continue
                    findings.extend(_segment_findings(
                        body, attrs,
                        "lock '%s' state" % lock, m.name, src.path))
                # declared atomic groups: whole-method transitions
                # (__init__ is construction, not a transition — a raise
                # there never leaks a half-written instance)
                for gname, attrs in groups.items():
                    if len(attrs) < 2:
                        continue
                    findings.extend(_segment_findings(
                        m.body, attrs,
                        "atomic group '%s'" % gname, m.name, src.path))
            else:
                findings.extend(_init_install_leaks(m, src, node.name))
    return findings


def _handler_rolls_back(tr: ast.Try) -> bool:
    """A handler that re-raises AND takes a rollback action (a call or
    an attribute reset), or a finally that runs cleanup calls, covers
    raises inside this try."""
    for h in tr.handlers:
        has_raise = any(isinstance(n, ast.Raise) for n in ast.walk(h))
        has_action = any(isinstance(n, (ast.Call, ast.Assign))
                         for n in ast.walk(h))
        if has_raise and has_action:
            return True
    return bool(tr.finalbody) and _has_call(tr.finalbody)


def _init_install_leaks(m, src: SourceFile, cls_name: str
                        ) -> list[Finding]:
    events: list[tuple] = []          # (kind, line, label, protect_ids)

    def calls(expr, stack):
        for c in _calls_in(expr):
            label = _fallible_label(c)
            if label is not None:
                events.append(("call", c.lineno, label, stack))

    def visit(body, stack):
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Try):
                sub = stack + ((id(st),) if _handler_rolls_back(st)
                               else ())
                visit(st.body, sub)
                for h in st.handlers:
                    visit(h.body, sub)
                visit(st.orelse, sub)
                visit(st.finalbody, stack)
                continue
            if isinstance(st, (ast.If, ast.While, ast.For, ast.AsyncFor)):
                calls(getattr(st, "test", None) or
                      getattr(st, "iter", None), stack)
                visit(st.body, stack)
                visit(st.orelse, stack)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    calls(item.context_expr, stack)
                visit(st.body, stack)
                continue
            # argument/value calls evaluate before the install arms
            calls(st, stack)
            if _install_for_stmt(src.lines, st):
                events.append(("install", st.lineno, None, stack))

    visit(m.body, ())
    findings: list[Finding] = []
    for i, ev in enumerate(events):
        if ev[0] != "install":
            continue
        for later in events[i + 1:]:
            # protection is judged at the CALL: if the raise lands
            # inside a try whose handler rolls back and re-raises, the
            # install is undone no matter where it was armed
            if later[0] == "call" and not later[3]:
                findings.append(Finding(
                    src.path, ev[1], RULE_INSTALL_LEAK,
                    "'%s.__init__' arms this global install and then "
                    "runs fallible '%s' with no rollback on the "
                    "raising path — a failed construction leaks the "
                    "install with no instance left to undo it; wrap "
                    "the tail in try/except that uninstalls (and "
                    "restores any prior state) before re-raising"
                    % (cls_name, later[2])))
                break
    return findings


# --------------------------------------------------------------------- #
# Analyzer plumbing                                                     #
# --------------------------------------------------------------------- #


def _analysis(ctx: LintContext) -> dict:
    bucket = ctx.bucket("ordering")
    if "order_findings" in bucket:
        return bucket
    an = _OrderAnalysis(ctx)
    an.run(ctx)
    bucket["order_findings"] = an.verify()
    bucket["contracts"] = set(an.contracts)
    bucket["events"] = set(an.events)
    return bucket


def _check_order(src: SourceFile, ctx: LintContext) -> list[Finding]:
    return []


def _finish_order(ctx: LintContext) -> list[Finding]:
    return list(_analysis(ctx)["order_findings"])


def static_order_table(root: str | None = None,
                       paths: tuple[str, ...] = ("opentsdb_tpu",)
                       ) -> dict:
    """{"contracts": {(a, b), ...}, "events": {name, ...}} — the static
    table tsdbsan's runtime order recorder cross-checks its per-trace
    event streams against (tools/sanitize/order.py), mirroring
    `blocking.static_request_paths`.  A line-regex scan, not a lint
    run: the cross-check only needs the declared NAMES, and it runs
    inside the sanitized session's wall-time budget — parsing the tree
    into ASTs there would eat the 2x overhead pin for nothing."""
    import os
    from tools.lint.core import REPO_ROOT
    base = root or REPO_ROOT
    contracts: set[tuple[str, str]] = set()
    events: set[str] = set()
    for top in paths:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(base, top)):
            dirnames[:] = [d for d in dirnames if not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                try:
                    with open(os.path.join(dirpath, fn), "r",
                              encoding="utf-8") as fh:
                        for line in fh:
                            if "# order" not in line:
                                continue
                            contracts.update(order_contracts(line))
                            events.update(order_events(line))
                except (OSError, UnicodeDecodeError):
                    continue
    return {"contracts": contracts, "events": events}


ORDER_ANALYZER = Analyzer(
    "order_contract", (RULE_ORDER,), _check_order, _finish_order)
ATOMICITY_ANALYZER = Analyzer(
    "failure_atomicity", (RULE_TORN, RULE_INSTALL_LEAK), _check_atomicity)
