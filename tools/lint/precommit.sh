#!/bin/sh
# tsdblint pre-commit wrapper: lint only what you touched.
#
# Install:   ln -s ../../tools/lint/precommit.sh .git/hooks/pre-commit
# Run ad hoc: tools/lint/precommit.sh [--san] [tsdblint args...]
#
# The whole tree is analyzed (the interprocedural analyzers need every
# function summary) but findings are reported only for files that
# differ from HEAD — so a dirty checkout never blocks your commit on
# someone else's debt, and the full-tree pass stays under the tier-1
# 30s budget (tests/test_lint_analyzers.py pins it).
#
# `--san` additionally runs the tsdbsan sanitized tier-1 subset
# (tools/sanitize/run.py --subset tier1) after a clean lint pass — the
# dynamic twin of the static gate.  Opt-in: it runs real concurrency
# tests and takes minutes, not seconds.
set -e
REPO_ROOT="$(cd "$(dirname "$0")/../.." && pwd)"
RUN_SAN=0
if [ "$1" = "--san" ]; then
    RUN_SAN=1
    shift
fi
python "$REPO_ROOT/tools/lint/run.py" --changed-only "$@"
if [ "$RUN_SAN" = "1" ]; then
    python "$REPO_ROOT/tools/sanitize/run.py" --subset tier1
fi
