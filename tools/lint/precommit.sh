#!/bin/sh
# tsdblint pre-commit wrapper: lint only what you touched.
#
# Install:   ln -s ../../tools/lint/precommit.sh .git/hooks/pre-commit
# Run ad hoc: tools/lint/precommit.sh
#
# The whole tree is analyzed (the interprocedural analyzers need every
# function summary) but findings are reported only for files that
# differ from HEAD — so a dirty checkout never blocks your commit on
# someone else's debt, and the full-tree pass stays under the tier-1
# 30s budget (tests/test_lint_analyzers.py pins it).
set -e
REPO_ROOT="$(cd "$(dirname "$0")/../.." && pwd)"
exec python "$REPO_ROOT/tools/lint/run.py" --changed-only "$@"
