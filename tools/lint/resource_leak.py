"""Resource-leak paths: sockets, files, executors, WAL handles.

A TSD leaks quietly: a socket left open per failed peer fetch, a WAL
file handle dropped on an early return, an executor that never shuts
down — each survives the request that created it and accumulates until
the fd table or the thread count kills the process.  This analyzer
walks every function in the serving/storage/tooling layers and checks
that an acquired resource reaches `close()` (or kin), a `with` block,
or a `try/finally` on all NON-exceptional exit paths.

Model (optimistic — a rule fires only when NO route to cleanup exists):

  acquire   `open(...)`, `socket.socket/create_connection`,
            `ThreadPoolExecutor/ProcessPoolExecutor`, `subprocess.Popen`,
            `gzip/bz2/lzma.open`, `os.fdopen`,
            `tempfile.*TemporaryFile` — bound to a LOCAL name.
  release   a `.close/.shutdown/.stop/.terminate/.kill/.wait/
            .communicate/.release/.join()` call on that name; a `with`
            context; a `try/finally` whose finally releases it (the
            name counts as protected for the whole try).
  escape    ownership transfer ends tracking: returned, yielded, stored
            into an attribute/subscript/container, passed as a call
            argument, or aliased — the receiver is responsible now.

Two findings:

  resource-leak          the function can finish with the resource open
                         (no release/escape anywhere after acquisition)
  resource-leak-return   an early `return` crosses a live resource that
                         a LATER line does release — the error path
                         leaks what the happy path closes

Trace spans (obs/trace.py) are an acquisition kind too: a `Span`
started via `obs_trace.begin(...)` or `parent.child(...)` must reach
`finish()` (or the explicit hand-finish `span.wall_ms = ...` the
estimated-children idiom uses), a `with`, or escape to another owner on
all paths — an unfinished span renders a forever-climbing wallMs at
every later /api/stats/query scrape until the trace closes it.  The
cluster fan-out's create-on-owner/finish-on-pool handoff is the
canonical ownership transfer: the span passes into `pool.submit(...)`
and the pool thread finishes it.

Scope: `opentsdb_tpu/tsd/`, `opentsdb_tpu/storage/`,
`opentsdb_tpu/tools/`, `opentsdb_tpu/query/`, `opentsdb_tpu/obs/` by
default.  Exceptional exits (raise) are out of scope by design — that
is what `with`/`finally` are for, and flagging every raise-crossing
would drown the real findings.
"""

from __future__ import annotations

import ast

from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_LEAK = "resource-leak"
RULE_LEAK_RETURN = "resource-leak-return"

LEAK_DIRS = ("opentsdb_tpu/tsd/", "opentsdb_tpu/storage/",
             "opentsdb_tpu/tools/", "opentsdb_tpu/query/",
             "opentsdb_tpu/obs/")

ACQUIRE_NAMES = {"open", "ThreadPoolExecutor", "ProcessPoolExecutor",
                 "Popen",
                 # spill-pool tier files (storage/spill.py): every
                 # handle must close or transfer ownership to the pool
                 "open_spill_file"}
ACQUIRE_ATTRS = {
    ("socket", "socket"), ("socket", "create_connection"),
    ("subprocess", "Popen"), ("gzip", "open"), ("bz2", "open"),
    ("lzma", "open"), ("io", "open"), ("os", "fdopen"),
    ("tempfile", "NamedTemporaryFile"), ("tempfile", "TemporaryFile"),
    # span starts: obs/trace.py's non-context-manager stage API
    ("obs_trace", "begin"), ("trace", "begin"),
    # spill files opened through the module alias
    ("spill", "open_spill_file"),
}
# method names that mint a new Span on ANY receiver (Span.child /
# Trace.current().child — the receiver varies, the contract doesn't)
SPAN_METHODS = {"child"}
RELEASERS = {"close", "shutdown", "stop", "terminate", "kill", "wait",
             "communicate", "release", "join", "quit", "detach",
             "finish"}
# attribute stores that hand-finish a span (finish() only fills wall_ms
# when it is still None — an explicit assignment IS the finish)
HAND_FINISH_ATTRS = {"wall_ms"}


def _acquire_kind(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name) and f.id in ACQUIRE_NAMES:
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and (f.value.id, f.attr) in ACQUIRE_ATTRS:
        return "%s.%s" % (f.value.id, f.attr)
    if isinstance(f, ast.Attribute) and f.attr in SPAN_METHODS:
        return "span.%s" % f.attr
    return None


def _find_acquire(expr: ast.expr) -> str | None:
    """The acquisition kind of an assignment's value expression: the
    call itself, or either arm of a conditional expression."""
    if isinstance(expr, ast.Call):
        return _acquire_kind(expr)
    if isinstance(expr, ast.IfExp):
        return _find_acquire(expr.body) or _find_acquire(expr.orelse)
    return None


class _FnLeaks:
    def __init__(self, fn, path: str):
        self.fn = fn
        self.path = path
        self.open: dict[str, tuple[int, str]] = {}
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        self._walk(self.fn.body, frozenset())
        for name, (line, kind) in self.open.items():
            self.findings.append(Finding(
                self.path, line, RULE_LEAK,
                "%s acquired by %r in '%s' never reaches close/with/"
                "finally — the handle outlives the function on every "
                "path" % (kind, name, self.fn.name)))
        return self.findings

    # -- name usage classification --------------------------------------

    def _released(self, st: ast.stmt) -> set[str]:
        """Names released by `.close()`-style calls anywhere in `st`,
        plus spans hand-finished by a `span.wall_ms = ...` store."""
        out = set()
        for node in ast.walk(st):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in RELEASERS \
                    and isinstance(node.func.value, ast.Name):
                out.add(node.func.value.id)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr in HAND_FINISH_ATTRS \
                            and isinstance(tgt.value, ast.Name):
                        out.add(tgt.value.id)
        return out

    def _escaped(self, st: ast.stmt) -> set[str]:
        """Names whose ownership transfers somewhere inside `st`."""
        out: set[str] = set()

        def note(e):
            if isinstance(e, ast.Name):
                out.add(e.id)

        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                for a in node.args:
                    note(a.value if isinstance(a, ast.Starred) else a)
                for kw in node.keywords:
                    note(kw.value)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None:
                    for sub in ast.walk(node.value):
                        note(sub)
            elif isinstance(node, ast.Assign):
                # alias, attribute store, container store
                if isinstance(node.value, ast.Name):
                    for tgt in node.targets:
                        if isinstance(tgt, (ast.Attribute, ast.Subscript,
                                            ast.Name)):
                            note(node.value)
                for sub in ast.walk(node.value):
                    if isinstance(sub, (ast.Tuple, ast.List, ast.Dict,
                                        ast.Set)):
                        for el in ast.iter_child_nodes(sub):
                            note(el)
            elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                for el in node.elts:
                    note(el)
            elif isinstance(node, ast.Dict):
                for el in list(node.keys) + list(node.values):
                    note(el)
        return out

    # -- statement walk --------------------------------------------------

    def _apply(self, st: ast.stmt) -> None:
        """Releases and escapes inside one statement."""
        for name in self._released(st) | self._escaped(st):
            self.open.pop(name, None)

    def _walk(self, stmts, protected: frozenset) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue                  # nested defs own their scopes
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                kind = _find_acquire(st.value)
                self._apply(st)
                if kind is not None \
                        and st.targets[0].id not in protected:
                    self.open[st.targets[0].id] = (st.lineno, kind)
                continue
            if isinstance(st, ast.Return):
                returned = {n.id for n in ast.walk(st)
                            if isinstance(n, ast.Name)}
                for name, (line, kind) in list(self.open.items()):
                    if name in returned:
                        self.open.pop(name)   # ownership to the caller
                        continue
                    if name in protected:
                        continue    # an enclosing finally releases it
                    self.findings.append(Finding(
                        self.path, st.lineno, RULE_LEAK_RETURN,
                        "return in '%s' crosses %s %r acquired earlier "
                        "and still open — this exit path leaks what a "
                        "later line releases" % (self.fn.name, kind,
                                                 name)))
                    self.open.pop(name)   # report each path-leak once
                continue
            if isinstance(st, ast.With):
                # `with open(...) as fh` manages itself
                self._apply_expr_only(st.items)
                self._walk(st.body, protected)
                continue
            if isinstance(st, ast.Try):
                # a finally that releases a name protects it everywhere
                # in the try — including acquisitions INSIDE the body
                # and early returns that cross them
                released = set()
                for fst in st.finalbody:
                    released |= self._released(fst) | self._escaped(fst)
                for name in released:
                    self.open.pop(name, None)
                inner = protected | released
                self._walk(st.body, inner)
                for h in st.handlers:
                    self._walk(h.body, inner)
                self._walk(st.orelse, inner)
                self._walk(st.finalbody, protected)
                # the finally has run once the try completes
                for name in released:
                    self.open.pop(name, None)
                continue
            if isinstance(st, (ast.If, ast.While)):
                self._apply_test(st.test)
                self._walk(st.body, protected)
                self._walk(st.orelse, protected)
                continue
            if isinstance(st, ast.For):
                self._apply_test(st.iter)
                self._walk(st.body, protected)
                self._walk(st.orelse, protected)
                continue
            self._apply(st)

    def _apply_test(self, expr: ast.expr) -> None:
        fake = ast.Expr(value=expr)
        self._apply(fake)

    def _apply_expr_only(self, items) -> None:
        for item in items:
            fake = ast.Expr(value=item.context_expr)
            self._apply(fake)
            if item.optional_vars is not None:
                # `as` target of a with: managed, never tracked
                if isinstance(item.optional_vars, ast.Name):
                    self.open.pop(item.optional_vars.id, None)


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    bucket = ctx.bucket("leak")
    dirs = tuple(bucket.get("paths", LEAK_DIRS))
    if not (src.path.startswith(dirs) or any(d in src.path
                                             for d in dirs)):
        return []
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_FnLeaks(node, src.path).run())
    return findings


ANALYZER = Analyzer("resource_leak", (RULE_LEAK, RULE_LEAK_RETURN), check)
