#!/usr/bin/env python3
"""tsdblint CLI.

    python tools/lint/run.py                      # lint opentsdb_tpu/
    python tools/lint/run.py --json               # machine-readable
    python tools/lint/run.py --update-baseline    # grandfather findings
    python tools/lint/run.py --no-baseline        # raw findings
    python tools/lint/run.py --update-doc         # regen docs/configuration.md
    python tools/lint/run.py path/to/file.py ...  # specific targets

Exit status: 0 = no findings beyond the baseline, 1 = new findings,
2 = usage/internal error.  The tier-1 gate (tests/test_lint_clean.py)
runs the same code in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint.core import (  # noqa: E402
    REPO_ROOT, apply_baseline, load_baseline, run_lint, save_baseline)

DEFAULT_PATHS = ["opentsdb_tpu"]
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tsdblint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: opentsdb_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--update-doc", action="store_true",
                    help="regenerate docs/configuration.md from "
                         "CONFIG_SCHEMA and exit")
    args = ap.parse_args(argv)

    if args.update_doc:
        from opentsdb_tpu.utils.config import generate_config_doc
        doc_path = os.path.join(REPO_ROOT, "docs", "configuration.md")
        os.makedirs(os.path.dirname(doc_path), exist_ok=True)
        with open(doc_path, "w", encoding="utf-8") as fh:
            fh.write(generate_config_doc())
        print("wrote %s" % os.path.relpath(doc_path, REPO_ROOT))
        return 0

    paths = args.paths or DEFAULT_PATHS
    findings = run_lint(paths)

    if args.update_baseline:
        save_baseline(findings, args.baseline)
        print("baseline updated: %d finding(s) grandfathered into %s"
              % (len(findings), os.path.relpath(args.baseline, REPO_ROOT)))
        return 0

    if not args.no_baseline:
        findings = apply_baseline(findings, load_baseline(args.baseline))

    if args.as_json:
        print(json.dumps([{"path": f.path, "line": f.line, "rule": f.rule,
                           "message": f.message} for f in findings],
                         indent=1))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print("\n%d finding(s)" % len(findings))
        else:
            print("tsdblint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
