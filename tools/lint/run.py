#!/usr/bin/env python3
"""tsdblint CLI.

    python tools/lint/run.py                      # lint opentsdb_tpu/
    python tools/lint/run.py --json               # machine-readable
    python tools/lint/run.py --sarif              # SARIF 2.1.0 output
    python tools/lint/run.py --changed-only       # findings in files
                                                  # touched vs HEAD only
    python tools/lint/run.py --update-baseline    # grandfather findings
    python tools/lint/run.py --no-baseline        # raw findings
    python tools/lint/run.py --update-doc         # regen docs/configuration.md
    python tools/lint/run.py --timings            # per-analyzer wall time
    python tools/lint/run.py --only effect_contract,dispatch_purity
                                                  # dev-loop subset
    python tools/lint/run.py path/to/file.py ...  # specific targets

`--changed-only` still ANALYZES the whole tree (the interprocedural
analyzers need every summary) but reports only findings located in
files `git` says differ from HEAD (staged, unstaged, or untracked) —
the pre-commit wiring (tools/lint/precommit.sh).

Exit status: 0 = no findings beyond the baseline, 1 = new findings,
2 = usage/internal error.  The tier-1 gate (tests/test_lint_clean.py)
runs the same code in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint.core import (  # noqa: E402
    REPO_ROOT, LintContext, apply_baseline, load_baseline, run_lint,
    save_baseline)

DEFAULT_PATHS = ["opentsdb_tpu"]
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tsdblint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: opentsdb_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--sarif", action="store_true",
                    help="emit findings as SARIF 2.1.0")
    ap.add_argument("--changed-only", action="store_true",
                    dest="changed_only",
                    help="report only findings in files changed vs HEAD "
                         "(whole tree is still analyzed)")
    ap.add_argument("--timings", action="store_true",
                    help="print the per-analyzer wall-time breakdown "
                         "(with --json: {\"findings\": ..., "
                         "\"timings\": ...})")
    ap.add_argument("--only", default=None, metavar="ANALYZER[,ANALYZER]",
                    help="run only the named analyzers (dev loop; "
                         "composes with --changed-only/--timings). "
                         "The pre-commit gate always runs all of them.")
    ap.add_argument("--update-doc", action="store_true",
                    help="regenerate docs/configuration.md from "
                         "CONFIG_SCHEMA and exit")
    args = ap.parse_args(argv)

    if args.update_doc:
        from opentsdb_tpu.obs import generate_metrics_doc
        from opentsdb_tpu.utils.config import generate_config_doc
        for fname, render in (("configuration.md", generate_config_doc),
                              ("metrics.md", generate_metrics_doc)):
            doc_path = os.path.join(REPO_ROOT, "docs", fname)
            os.makedirs(os.path.dirname(doc_path), exist_ok=True)
            with open(doc_path, "w", encoding="utf-8") as fh:
                fh.write(render())
            print("wrote %s" % os.path.relpath(doc_path, REPO_ROOT))
        return 0

    paths = args.paths or DEFAULT_PATHS
    analyzers = None
    if args.only:
        from tools.lint.core import get_analyzers
        wanted = [n.strip() for n in args.only.split(",") if n.strip()]
        by_name = {a.name: a for a in get_analyzers()}
        unknown = [n for n in wanted if n not in by_name]
        if unknown:
            print("tsdblint: unknown analyzer(s): %s (known: %s)"
                  % (", ".join(unknown), ", ".join(sorted(by_name))),
                  file=sys.stderr)
            return 2
        analyzers = [by_name[n] for n in wanted]
    ctx = LintContext(REPO_ROOT)
    findings = run_lint(paths, ctx=ctx, analyzers=analyzers)
    timings = dict(sorted(ctx.bucket("timings").items(),
                          key=lambda kv: -kv[1])) if args.timings else None

    if args.update_baseline:
        save_baseline(findings, args.baseline)
        print("baseline updated: %d finding(s) grandfathered into %s"
              % (len(findings), os.path.relpath(args.baseline, REPO_ROOT)))
        return 0

    if not args.no_baseline:
        findings = apply_baseline(findings, load_baseline(args.baseline))

    if args.changed_only:
        changed = _changed_files()
        findings = [f for f in findings if f.path in changed]

    if args.sarif:
        from tools.lint.core import get_analyzers
        from tools.lint.sarif import to_sarif
        print(json.dumps(to_sarif(findings, get_analyzers()), indent=1))
        if timings is not None:
            _print_timings(timings, stream=sys.stderr)
    elif args.as_json:
        payload = [{"path": f.path, "line": f.line, "rule": f.rule,
                    "message": f.message} for f in findings]
        if timings is not None:
            # a bare `--json` stays a bare list (stable machine
            # interface); --timings opts into the wrapped object
            print(json.dumps({"findings": payload, "timings": timings},
                             indent=1))
        else:
            print(json.dumps(payload, indent=1))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print("\n%d finding(s)" % len(findings))
        else:
            print("tsdblint: clean")
        if timings is not None:
            _print_timings(timings, stream=sys.stdout)
    return 1 if findings else 0


def _print_timings(timings: dict, stream) -> None:
    total = sum(timings.values())
    print("\nper-analyzer wall time (%.2fs total):" % total, file=stream)
    for name, secs in timings.items():
        print("  %-28s %7.3fs" % (name, secs), file=stream)


def _changed_files() -> set[str]:
    """Repo-relative posix paths git reports as differing from HEAD:
    staged + unstaged + untracked.  A failing git command degrades
    LOUDLY (stderr warning) and keeps whatever the other command
    reported — a transient `git ls-files` hiccup must not silently
    filter every finding out of the pre-commit gate."""
    import subprocess
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  cwd=REPO_ROOT, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            print("tsdblint: warning: %s failed (%s) — changed-only "
                  "file set may be incomplete" % (" ".join(cmd), e),
                  file=sys.stderr)
            continue
        if proc.returncode != 0:
            print("tsdblint: warning: %s exited %d — changed-only "
                  "file set may be incomplete"
                  % (" ".join(cmd), proc.returncode), file=sys.stderr)
            continue
        out.update(line.strip().replace(os.sep, "/")
                   for line in proc.stdout.splitlines() if line.strip())
    return out


if __name__ == "__main__":
    sys.exit(main())
