"""SARIF 2.1.0 output for tsdblint findings.

One run, one tool (`tsdblint`), one result per finding.  Rule metadata
is collected from the registered analyzers so viewers (GitHub code
scanning, VS Code SARIF viewer) can group by rule.  Messages are the
same line-number-free strings the baseline keys on; the physical
location carries the line.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

_RULE_DESCRIPTIONS = {
    "jax-host-sync": "Device sync on a traced value in jit-reachable code",
    "jax-tracer-branch": "Python branch on a traced value",
    "jax-jit-per-call": "jax.jit constructed per call",
    "jax-int64-no-x64-guard": "jnp.int64 without an x64 guard",
    "lock-missing-annotation": "Lock-guarded attribute lacks guarded-by",
    "lock-unguarded-mutation": "Guarded attribute mutated without lock",
    "lock-order-cycle": "Lock acquisition order cycle",
    "config-unknown-key": "Config key read but not declared in schema",
    "config-type-mismatch": "Typed config getter disagrees with schema",
    "config-dead-key": "Schema key no code reads",
    "except-swallow": "Broad except neither logs, counts, nor re-raises",
    "shape-contract-mismatch": "Caller disagrees with a # shape: contract",
    "shape-dtype-narrowing": "64-bit value narrowed to 32-bit unguarded",
    "shape-axis-mismatch": "Reduction axis outside the operand's rank",
    "shape-divergent-dtypes": "where/concat operands of divergent dtypes",
    "taint-unsanitized-alloc":
        "Request field sizes an allocation with no limits sanitizer",
    "resource-leak": "Acquired resource never reaches close/with/finally",
    "resource-leak-return": "Early return crosses a live resource",
    "effect-violation":
        "Transitive effects exceed the declared # effects: contract",
    "effect-observe-leak":
        "Accounting effect not dominated by the observe gate",
    "effect-bad-annotation": "Malformed # effects: contract",
    "dispatch-reachable":
        "Device dispatch reachable from a dispatch-free entry",
    "permit-reachable":
        "Admission permit acquisition reachable from a read-only entry",
    "parse-error": "File failed to parse",
    # tsdbsan (tools/sanitize) — the runtime layer shares this emitter
    "san-unguarded-mutation":
        "Guarded attribute mutated at runtime without its lock",
    "san-lockset-race": "Multi-thread writes share no common lock",
    "san-lock-order-inversion": "Runtime lock acquisition order cycle",
    "san-deadlock": "Live wait-for cycle between threads",
    "san-recompile-after-warmup": "Kernel compiled again after warmup",
    "san-host-sync": "Unsanctioned device->host transfer in steady state",
    "san-stale-static-edge": "Static lock-order edge never observed",
    "san-lint-gap": "Runtime lock-order edge invisible to lint",
    "san-effect-violation":
        "Runtime effect on an explain-tagged request outside the "
        "static contract",
}


def to_sarif(findings, analyzers, tool_name: str = "tsdblint",
             levels: dict | None = None) -> dict:
    """`levels` maps a Finding fingerprint to a SARIF level; absent
    entries default to "error" (every lint finding is an error; tsdbsan
    passes "note" for its cross-check reports)."""
    levels = levels or {}
    rule_ids = sorted({f.rule for f in findings}
                      | {r for a in analyzers for r in a.rules})
    rules = [{
        "id": rid,
        "shortDescription": {
            "text": _RULE_DESCRIPTIONS.get(rid, rid)},
    } for rid in rule_ids]
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": levels.get(f.fingerprint, "error"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    # repo-relative URI, no originalUriBaseIds: the
                    # consumer (code-scanning upload, SARIF viewer
                    # workspace root) resolves against its own checkout
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        }
        related = getattr(f, "related", ())
        if related:
            # the interprocedural route to the sink (call chain, effect
            # origin) — viewers show the path, not just the last line
            result["relatedLocations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": path},
                    "region": {"startLine": max(line, 1)},
                },
                "message": {"text": note},
            } for path, line, note in related]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "rules": rules,
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }
