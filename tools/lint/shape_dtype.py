"""Shape/dtype abstract interpretation over the JAX kernel layers.

The pipeline's silent failure modes are numeric, not crashes: an int64
ms-timestamp narrowed to int32 wraps, a float64 accumulator demoted to
float32 loses the reference's Java-double contract, an axis mixed up
between (series, time) and (time, series) aggregates the wrong way and
only surfaces as wrong numbers.  This analyzer tracks symbolic shapes
and dtypes through `jnp`/`np` expressions, seeded by lightweight
`# shape:` contract comments on kernel signatures, and checks callers
against those contracts across functions.

Contract grammar — comment line(s) directly above the `def` (multiple
lines merge; dict entries use dotted names):

    # shape: ts[S,N] i64, val[S,N] f64, mask[S,N] bool -> [S,W] f64
    # shape: wargs.first[] i64, wargs.nwin[] i32

  * dims: comma-separated symbols; `[]` = scalar; `*` = unconstrained
  * dtypes: i64 i32 f64 f32 bool any
  * returns: `-> [dims] dtype` or `-> ([dims] dtype, [dims] dtype, ...)`

Rules:

  shape-contract-mismatch   a call argument whose inferred rank differs
                            from the contract, or whose dim symbols bind
                            a callee symbol inconsistently across the
                            call's arguments (the axis-transpose bug),
                            or whose dtype conflicts in kind/width with
                            the declaration (widening direction).
  shape-dtype-narrowing     a 64-bit value cast to the 32-bit dtype of
                            the same kind (`.astype(jnp.int32)`,
                            `jnp.asarray(x, jnp.float32)`, or passed to
                            a contract parameter declared 32-bit is
                            exempt — that narrowing is declared), with
                            no `jnp.clip(...)` saturation wrapper.
                            Unclipped int64->int32 on ms timestamps is
                            exactly the truncation `require_x64()`
                            exists to prevent.
  shape-axis-mismatch       a reduction/concat `axis=` literal outside
                            the operand's known rank.
  shape-divergent-dtypes    `jnp.where`/`concatenate`/`stack` mixing
                            two operands of known different dtypes
                            (python scalars are weak-typed and exempt).

Inference is deliberately conservative: a rule only fires when both
sides are KNOWN — unknown shapes/dtypes never produce findings.
Scope: `opentsdb_tpu/ops/` and `opentsdb_tpu/parallel/` by default.
"""

from __future__ import annotations

import ast
import re

from tools.lint.callgraph import get_callgraph
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_CONTRACT = "shape-contract-mismatch"
RULE_NARROW = "shape-dtype-narrowing"
RULE_AXIS = "shape-axis-mismatch"
RULE_DIVERGENT = "shape-divergent-dtypes"

SHAPE_DIRS = ("opentsdb_tpu/ops/", "opentsdb_tpu/parallel/")

_CONTRACT_RE = re.compile(r"^\s*#\s*shape:\s*(.+?)\s*$")
_PARAM_RE = re.compile(
    r"(?P<name>\w+(?:\.\w+)?)\s*\[(?P<dims>[^\]]*)\]\s*(?P<dtype>\w+)")
_RET_RE = re.compile(r"\[(?P<dims>[^\]]*)\]\s*(?P<dtype>\w+)")

DTYPES = {"i64": "i64", "i32": "i32", "f64": "f64", "f32": "f32",
          "bool": "bool", "any": None}

_DTYPE_ATTRS = {"int64": "i64", "int32": "i32", "float64": "f64",
                "float32": "f32", "bool_": "bool", "uint8": "i32",
                "int16": "i32", "float16": "f32"}

REDUCERS = {"sum", "mean", "max", "min", "prod", "any", "all",
            "argmax", "argmin", "nanmax", "nanmin", "nansum"}
SCANS = {"cumsum", "cumprod", "sort", "flip", "diff",
         "associative_scan"}
JOINERS = {"where", "concatenate", "stack", "append"}

_WIDER = {"i32": "i64", "f32": "f64"}


class Abstract:
    """(shape, dtype, clipped) lattice value; None = unknown slot.
    `clipped` marks values already saturated by jnp.clip — narrowing
    them is deliberate range control, not silent truncation."""
    __slots__ = ("shape", "dtype", "clipped")

    def __init__(self, shape=None, dtype=None, clipped=False):
        self.shape = shape          # tuple of dim symbols, or None
        self.dtype = dtype          # "i64" | ... | None
        self.clipped = clipped

    def __repr__(self):
        return "Abstract(%r, %r, clipped=%r)" % (self.shape, self.dtype,
                                                 self.clipped)


UNKNOWN = Abstract()


def _promote(a: str | None, b: str | None) -> str | None:
    if a is None or b is None:
        return None
    if a == b:
        return a
    order = {"bool": 0, "i32": 1, "i64": 2, "f32": 3, "f64": 4}
    if a in order and b in order:
        return a if order[a] >= order[b] else b
    return None


class Contract:
    __slots__ = ("params", "returns", "qname")

    def __init__(self, qname: str):
        self.qname = qname
        self.params: dict[str, Abstract] = {}
        self.returns: list[Abstract] = []


def parse_contract(lines: list[str], def_line: int, qname: str
                   ) -> Contract | None:
    """Contract from `# shape:` comment lines directly above the def
    (scanning upward past decorators and other comments stops at the
    first blank/code line that is neither)."""
    specs: list[str] = []
    i = def_line - 2                      # 0-based line above the def
    while i >= 0:
        line = lines[i]
        m = _CONTRACT_RE.match(line)
        if m:
            specs.append(m.group(1))
            i -= 1
            continue
        stripped = line.strip()
        if stripped.startswith("@") or stripped.startswith("#"):
            i -= 1
            continue
        break
    if not specs:
        return None
    out = Contract(qname)
    for spec in reversed(specs):
        if "->" in spec:
            params_part, ret_part = spec.split("->", 1)
        else:
            params_part, ret_part = spec, ""
        for m in _PARAM_RE.finditer(params_part):
            dims = tuple(d.strip() for d in m.group("dims").split(",")
                         if d.strip())
            dt = DTYPES.get(m.group("dtype"))
            if m.group("dtype") not in DTYPES:
                continue
            out.params[m.group("name")] = Abstract(dims, dt)
        for m in _RET_RE.finditer(ret_part):
            dims = tuple(d.strip() for d in m.group("dims").split(",")
                         if d.strip())
            dt = DTYPES.get(m.group("dtype"))
            if m.group("dtype") not in DTYPES:
                continue
            out.returns.append(Abstract(dims, dt))
    return out if (out.params or out.returns) else None


def _dtype_of_node(node: ast.expr) -> str | None:
    """jnp.int64 / np.float32 / bool -> abstract dtype."""
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_ATTRS:
        return _DTYPE_ATTRS[node.attr]
    if isinstance(node, ast.Name):
        if node.id == "bool":
            return "bool"
        if node.id in _DTYPE_ATTRS:
            return _DTYPE_ATTRS[node.id]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {"int64": "i64", "int32": "i32", "float64": "f64",
                "float32": "f32", "bool": "bool"}.get(node.value)
    return None


def _comparable(a: str, b: str) -> bool:
    """Two dim symbols share provenance: both caller-local names, or
    both derived from the SAME contracted callee's return."""
    if "@" in a or "@" in b:
        return ("@" in a and "@" in b
                and a.split("@", 1)[1] == b.split("@", 1)[1])
    return True


def _np_mod(node: ast.expr) -> bool:
    return isinstance(node, ast.Name) and node.id in ("np", "jnp",
                                                      "numpy", "lax")


class _FnCheck:
    """Infer abstract values through one function; check call sites."""

    def __init__(self, fi, graph, contracts, src: SourceFile | None):
        self.fi = fi
        self.graph = graph
        self.contracts = contracts
        self.src = src
        self.env: dict[str, Abstract] = {}
        self.findings: list[Finding] = []
        self._fresh = 0
        contract = contracts.get(fi.qname)
        if contract is not None:
            for name, av in contract.params.items():
                self.env[name] = Abstract(av.shape, av.dtype)

    # -- inference -------------------------------------------------------

    def _key(self, e: ast.expr) -> str | None:
        """Env key for a Name or param-dict subscript (wargs["first"])."""
        if isinstance(e, ast.Name):
            return e.id
        if isinstance(e, ast.Subscript) and isinstance(e.value, ast.Name) \
                and isinstance(e.slice, ast.Constant) \
                and isinstance(e.slice.value, str):
            return "%s.%s" % (e.value.id, e.slice.value)
        return None

    def infer(self, e: ast.expr) -> Abstract:
        key = self._key(e)
        if key is not None and key in self.env:
            return self.env[key]
        if isinstance(e, ast.Call):
            return self._infer_call(e)
        if isinstance(e, ast.BinOp):
            left = self.infer(e.left)
            right = self.infer(e.right)
            lw = isinstance(e.left, ast.Constant)
            rw = isinstance(e.right, ast.Constant)
            if isinstance(e.op, ast.Div):
                # true division: int operands promote to f64; known
                # floats promote among themselves (f32/f32 -> f32)
                if left.dtype is None or right.dtype is None:
                    dt = None
                elif left.dtype.startswith("f") \
                        and right.dtype.startswith("f"):
                    dt = _promote(left.dtype, right.dtype)
                else:
                    dt = "f64"
            elif lw and not rw:
                dt = right.dtype          # python scalars are weak
            elif rw and not lw:
                dt = left.dtype
            else:
                dt = _promote(left.dtype, right.dtype)
            shape = left.shape if left.shape is not None else right.shape
            if left.shape is not None and right.shape is not None \
                    and left.shape != right.shape:
                shape = None              # broadcast: unknown
            return Abstract(shape, dt)
        if isinstance(e, ast.UnaryOp):
            return self.infer(e.operand)
        if isinstance(e, ast.Compare):
            base = self.infer(e.left)
            return Abstract(base.shape, "bool")
        if isinstance(e, ast.IfExp):
            a, b = self.infer(e.body), self.infer(e.orelse)
            return Abstract(a.shape if a.shape == b.shape else None,
                            a.dtype if a.dtype == b.dtype else None)
        if isinstance(e, ast.Subscript):
            return self._infer_subscript(e)
        if isinstance(e, ast.Attribute):
            if e.attr in ("T",):
                base = self.infer(e.value)
                if base.shape is not None:
                    return Abstract(tuple(reversed(base.shape)),
                                    base.dtype)
            return UNKNOWN
        return UNKNOWN

    def _infer_subscript(self, e: ast.Subscript) -> Abstract:
        base = self.infer(e.value)
        if base.shape is None:
            return Abstract(None, base.dtype)
        idx = e.slice
        items = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        dims = list(base.shape)
        out: list[str] = []
        pos = 0
        for it in items:
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(self._fresh_dim())
                continue
            if pos >= len(dims):
                return Abstract(None, base.dtype)
            if isinstance(it, ast.Slice):
                out.append(dims[pos])     # sliced dim keeps its symbol
                pos += 1
            elif isinstance(it, ast.Constant) and isinstance(it.value,
                                                             int):
                pos += 1                  # integer index drops the dim
            else:
                return Abstract(None, base.dtype)
        out.extend(dims[pos:])
        return Abstract(tuple(out), base.dtype)

    def _fresh_dim(self) -> str:
        self._fresh += 1
        return "?%d" % self._fresh

    def _shape_from_tuple(self, node: ast.expr) -> tuple | None:
        """A literal shape tuple (s, w) -> symbolic dims from names."""
        elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
            else [node]
        dims = []
        for el in elts:
            if isinstance(el, ast.Name):
                dims.append(el.id)
            elif isinstance(el, ast.Constant) and isinstance(el.value,
                                                             int):
                dims.append(str(el.value))
            else:
                dims.append(self._fresh_dim())
        return tuple(dims)

    def _infer_call(self, call: ast.Call) -> Abstract:
        f = call.func
        # x.astype(d)
        if isinstance(f, ast.Attribute) and f.attr == "astype":
            base = self.infer(f.value)
            dt = _dtype_of_node(call.args[0]) if call.args else None
            self._check_narrowing(call, f.value, base, dt)
            return Abstract(base.shape, dt, clipped=base.clipped)
        if isinstance(f, ast.Attribute) and _np_mod(f.value):
            return self._infer_np_call(call, f)
        # contracted callee -> declared return
        for info, is_ctor, _cls in self.graph.resolve(call, self.fi):
            if info is None or is_ctor:
                continue
            contract = self.contracts.get(info.qname)
            if contract is None:
                continue
            subst = self._check_contract_call(call, info, contract)
            if len(contract.returns) == 1:
                r = contract.returns[0]
                return Abstract(self._map_dims(r.shape, subst, info),
                                r.dtype)
            return UNKNOWN
        return UNKNOWN

    def _map_dims(self, dims, subst, info) -> tuple | None:
        if dims is None:
            return None
        return tuple(subst.get(d, "%s@%s" % (d, info.name)) if d != "*"
                     else self._fresh_dim() for d in dims)

    def _infer_np_call(self, call: ast.Call, f: ast.Attribute) -> Abstract:
        name = f.attr
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        dt = None
        if "dtype" in kw:
            dt = _dtype_of_node(kw["dtype"])
        if name in ("zeros", "ones", "empty", "full"):
            if dt is None:
                dtpos = 2 if name == "full" else 1
                if len(call.args) > dtpos:
                    dt = _dtype_of_node(call.args[dtpos])
            shape = (self._shape_from_tuple(call.args[0])
                     if call.args else None)
            return Abstract(shape, dt)
        if name in ("zeros_like", "ones_like", "full_like", "empty_like"):
            base = self.infer(call.args[0]) if call.args else UNKNOWN
            return Abstract(base.shape, dt or base.dtype)
        if name in ("asarray", "array"):
            base = self.infer(call.args[0]) if call.args else UNKNOWN
            if len(call.args) > 1 and dt is None:
                dt = _dtype_of_node(call.args[1])
            if dt is not None and call.args:
                self._check_narrowing(call, call.args[0], base, dt)
            return Abstract(base.shape, dt or base.dtype)
        if name == "arange":
            n = call.args[0] if call.args else None
            dim = n.id if isinstance(n, ast.Name) else self._fresh_dim()
            return Abstract((dim,), dt)
        if name == "clip":
            base = self.infer(call.args[0]) if call.args else UNKNOWN
            return Abstract(base.shape, base.dtype, clipped=True)
        if name in REDUCERS or name in SCANS:
            base = self.infer(call.args[0]) if call.args else UNKNOWN
            axis = self._axis_of(call)
            self._check_axis(call, name, base, axis)
            if name in SCANS or axis is None:
                return base
            if base.shape is not None and kw.get("keepdims") is None:
                dims = list(base.shape)
                if -len(dims) <= axis < len(dims):
                    del dims[axis]
                    dt2 = ("bool" if name in ("any", "all") else
                           "i32" if name in ("argmax", "argmin")
                           else base.dtype)
                    return Abstract(tuple(dims), dt2)
            return Abstract(None, base.dtype)
        if name in JOINERS:
            return self._infer_joiner(call, name)
        if name == "searchsorted":
            return UNKNOWN
        if name in ("int64", "int32", "float64", "float32"):
            base = self.infer(call.args[0]) if call.args else UNKNOWN
            dt = _DTYPE_ATTRS[name]
            if call.args:
                self._check_narrowing(call, call.args[0], base, dt)
            return Abstract(base.shape, dt)
        return UNKNOWN

    @staticmethod
    def _axis_of(call: ast.Call) -> int | None:
        for k in call.keywords:
            if k.arg == "axis" and isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, int):
                return k.value.value
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, int):
            return call.args[1].value
        return None

    def _infer_joiner(self, call: ast.Call, name: str) -> Abstract:
        if name == "where":
            operands = call.args[1:3]
        else:
            first = call.args[0] if call.args else None
            operands = (first.elts if isinstance(first, (ast.Tuple,
                                                         ast.List))
                        else [])
        known = []
        for op in operands:
            if isinstance(op, ast.Constant):
                continue                  # weak python scalar
            av = self.infer(op)
            if av.dtype is not None:
                known.append((op, av))
        if len(known) >= 2:
            dts = {av.dtype for _, av in known}
            if len(dts) > 1:
                self._emit(call.lineno, RULE_DIVERGENT,
                           "jnp.%s mixes operands of divergent dtypes "
                           "(%s) in '%s': the silent promotion is a "
                           "different numeric contract per branch — "
                           "align dtypes explicitly"
                           % (name, "/".join(sorted(dts)), self.fi.name))
        if known:
            av = known[0][1]
            dt = known[0][1].dtype
            for _, other in known[1:]:
                dt = _promote(dt, other.dtype)
            return Abstract(av.shape, dt)
        return UNKNOWN

    # -- rule checks -----------------------------------------------------

    def _check_narrowing(self, call: ast.Call, operand: ast.expr,
                         base: Abstract, target: str | None) -> None:
        if target not in ("i32", "f32") or base.dtype is None:
            return
        if base.dtype != _WIDER[target]:
            return
        if base.clipped:
            return                    # already saturated by jnp.clip
        # jnp.clip(...) directly under the cast saturates deliberately
        if isinstance(operand, ast.Call) \
                and isinstance(operand.func, ast.Attribute) \
                and operand.func.attr == "clip":
            return
        self._emit(call.lineno, RULE_NARROW,
                   "%s value narrowed to %s in '%s' without a jnp.clip "
                   "saturation guard: out-of-range values wrap silently "
                   "(ms timestamps truncate) — clip to the target range "
                   "first, or declare the narrowing in a # shape: "
                   "contract" % (base.dtype, target, self.fi.name))

    def _check_axis(self, call: ast.Call, name: str, base: Abstract,
                    axis: int | None) -> None:
        if axis is None or base.shape is None:
            return
        rank = len(base.shape)
        if not (-rank <= axis < rank):
            self._emit(call.lineno, RULE_AXIS,
                       "jnp.%s over axis %d of a rank-%d value "
                       "[%s] in '%s': axis is out of range"
                       % (name, axis, rank, ",".join(base.shape),
                          self.fi.name))

    def _check_contract_call(self, call: ast.Call, info, contract
                             ) -> dict:
        """Unify args against the callee contract; returns the dim
        substitution (callee symbol -> caller symbol)."""
        params = info.params
        mapped: list[tuple[str, ast.expr]] = []
        pos = [p for p in params if p != "self"]
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred) or i >= len(pos):
                break
            mapped.append((pos[i], arg))
        for k in call.keywords:
            if k.arg in params:
                mapped.append((k.arg, k.value))
        subst: dict[str, str] = {}
        for pname, arg in mapped:
            decl = contract.params.get(pname)
            if decl is None:
                continue
            av = self.infer(arg)
            self._unify(call, info, pname, decl, av, arg, subst)
            # dict-entry sub-contracts: wargs.first etc, checked when
            # the caller passes a dict built of known values — skipped
            # here (the callee-side seeding enforces them)
        return subst

    def _unify(self, call, info, pname, decl: Abstract, av: Abstract,
               arg: ast.expr, subst: dict) -> None:
        if decl.dtype is not None and av.dtype is not None \
                and decl.dtype != av.dtype:
            if _WIDER.get(decl.dtype) == av.dtype:
                # declared-32-bit parameter: the narrowing is part of
                # the contract, not a finding
                pass
            else:
                self._emit(call.lineno, RULE_CONTRACT,
                           "'%s' passes a %s value where %s.%s declares "
                           "%s for parameter '%s'"
                           % (self.fi.name, av.dtype, info.name, pname,
                              decl.dtype, pname))
        if decl.shape is None or av.shape is None:
            return
        if len(decl.shape) != len(av.shape):
            self._emit(call.lineno, RULE_CONTRACT,
                       "'%s' passes a rank-%d value [%s] where %s.%s "
                       "declares rank-%d [%s] for parameter '%s'"
                       % (self.fi.name, len(av.shape),
                          ",".join(av.shape), info.name, pname,
                          len(decl.shape), ",".join(decl.shape), pname))
            return
        for d_sym, a_sym in zip(decl.shape, av.shape):
            if d_sym == "*" or a_sym.startswith("?"):
                continue
            bound = subst.get(d_sym)
            if bound is None:
                subst[d_sym] = a_sym
            elif bound != a_sym and _comparable(bound, a_sym):
                # (symbols of different provenance — a caller-local size
                # name vs a contract-derived one — are incomparable;
                # only same-provenance disagreement is an axis bug)
                self._emit(call.lineno, RULE_CONTRACT,
                           "'%s' call to %s binds contract dim '%s' to "
                           "both '%s' and '%s' — axis semantics "
                           "disagree with the callee's summary"
                           % (self.fi.name, info.name, d_sym, bound,
                              a_sym))

    def _emit(self, line: int, rule: str, message: str) -> None:
        self.findings.append(Finding(self.fi.path, line, rule, message))

    # -- driver ----------------------------------------------------------

    def run(self) -> list[Finding]:
        # two passes: the first settles the env (names used before their
        # inference stabilizes), the second emits
        for _ in range(2):
            self.findings = []
            self._walk(self.fi.node.body)
        seen = set()
        out = []
        for f in self.findings:
            if f not in seen:
                seen.add(f)
                out.append(f)
        return out

    def _walk(self, stmts) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(st.body)       # nested: shared env
                continue
            if isinstance(st, ast.Assign):
                av = self.infer(st.value)
                for tgt in st.targets:
                    self._bind(tgt, av, st.value)
                continue
            if isinstance(st, ast.AnnAssign) and st.value is not None:
                self._bind(st.target, self.infer(st.value), st.value)
                continue
            if isinstance(st, ast.AugAssign):
                self.infer(st.value)
                continue
            if isinstance(st, ast.Expr):
                self.infer(st.value)
                continue
            if isinstance(st, ast.Return):
                if st.value is not None:
                    self.infer(st.value)
                continue
            if isinstance(st, (ast.If, ast.While)):
                self.infer(st.test)
                self._walk(st.body)
                self._walk(st.orelse)
                continue
            if isinstance(st, ast.For):
                self.infer(st.iter)
                self._walk(st.body)
                self._walk(st.orelse)
                continue
            if isinstance(st, ast.With):
                for item in st.items:
                    self.infer(item.context_expr)
                self._walk(st.body)
                continue
            if isinstance(st, ast.Try):
                self._walk(st.body)
                for h in st.handlers:
                    self._walk(h.body)
                self._walk(st.orelse)
                self._walk(st.finalbody)
                continue

    def _bind(self, tgt, av: Abstract, value: ast.expr) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = av
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            # only a contracted multi-return unpacks precisely
            if isinstance(value, ast.Call):
                for info, is_ctor, _c in self.graph.resolve(value,
                                                            self.fi):
                    if info is None or is_ctor:
                        continue
                    contract = self.contracts.get(info.qname)
                    if contract and len(contract.returns) == len(
                            tgt.elts):
                        for el, r in zip(tgt.elts, contract.returns):
                            if isinstance(el, ast.Name):
                                self.env[el.id] = Abstract(
                                    self._map_dims(r.shape, {}, info),
                                    r.dtype)
                        return
            for el in tgt.elts:
                if isinstance(el, ast.Name):
                    self.env[el.id] = UNKNOWN


def finish(ctx: LintContext) -> list[Finding]:
    graph = get_callgraph(ctx)
    bucket = ctx.bucket("shape")
    dirs = tuple(bucket.get("paths", SHAPE_DIRS))
    src_by_path = {src.path: src for src in ctx.files}
    contracts: dict[str, Contract] = {}
    for fi in graph.funcs.values():
        src = src_by_path.get(fi.path)
        if src is None:
            continue
        c = parse_contract(src.lines, fi.node.lineno, fi.qname)
        if c is not None:
            contracts[fi.qname] = c
    findings: list[Finding] = []
    for fi in graph.funcs.values():
        if ".<nested>." in fi.qname:
            continue
        in_scope = fi.path.startswith(dirs) or any(d in fi.path
                                                   for d in dirs)
        if not in_scope:
            continue
        findings.extend(
            _FnCheck(fi, graph, contracts, src_by_path.get(fi.path)).run())
    return sorted(set(findings))


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    return []


ANALYZER = Analyzer(
    "shape_dtype",
    (RULE_CONTRACT, RULE_NARROW, RULE_AXIS, RULE_DIVERGENT),
    check, finish)
