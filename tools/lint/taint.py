"""Untrusted-input taint: request fields -> allocation-size expressions.

The costliest way a query kills a TSD is not a crash but an allocation:
a user-controlled range/interval/cardinality sizes a `jnp.zeros`, a
window-edge vector, or a Python list preallocation, and the host or the
device OOMs before any budget is consulted.  This analyzer tracks
request data interprocedurally from the parse layer to the kernels:

  sources     HttpQuery accessors (`get_query_string_param[s]`,
              `required_query_string_param`, `json_body`), serializer
              `parse_*_v1` calls, and the telnet `words`/`block`
              parameters of `execute_telnet*`/`import_telnet_point`.
  sinks       size arguments of `np`/`jnp` `zeros/full/empty/ones/
              arange`, list preallocation (`[x] * n`), and `range()`
              loop bounds — in files under SINK_DIRS (the kernel,
              storage, and planner layers).
  sanitizers  `query/limits.py` budget enforcement: a `.charge(...)`
              call, or an `if` guard comparing against
              `get_data_points_limit`/`get_byte_limit` that raises —
              either one, lexically before the sink/call on the route —
              plus `min(...)` clamps, which launder the clamped value.

A finding fires in the function where request data ENTERS (a source
call, or a call returning request-derived data) and then reaches a sink
— directly, or through a callee whose parameter provably flows to a
sink — with no sanitizer on any hop of that route.  Flow through
function returns, constructor captures (`TSQuery(start=tainted)` taints
the instance), attribute loads on tainted objects, and `while`-loop
control dependence (the `pad_pow2` idiom: the loop bound controls the
result) is tracked; `if` branches are not treated as implicit flows.

Whole-program: runs in finish() over every scanned file, to a fixpoint
over per-function summaries (tainted-return labels, params-that-reach-
sinks, inferred parameter/return class types for method resolution).
"""

from __future__ import annotations

import ast

from tools.lint.callgraph import get_callgraph
from tools.lint.core import Analyzer, Finding, LintContext, SourceFile

RULE_TAINT = "taint-unsanitized-alloc"

SOURCE_ATTRS = {
    "get_query_string_param", "get_query_string_params",
    "required_query_string_param", "json_body",
}
SOURCE_ATTR_PREFIXES = ("parse_put", "parse_query", "parse_suggest",
                        "parse_annotation", "parse_uid", "parse_histogram")
TELNET_FUNCS = {"execute_telnet", "import_telnet_point",
                "execute_telnet_batch"}
TELNET_PARAMS = {"words", "block"}

ALLOC_FUNCS = {"zeros", "full", "empty", "ones", "arange"}
ALLOC_MODULES = {"np", "jnp", "numpy"}

SANITIZER_CHARGE = {"charge"}
SANITIZER_LIMIT_GETTERS = {"get_data_points_limit", "get_byte_limit"}
# len() is deliberately clean: the length of data the request ALREADY
# shipped (or the store already holds) is proportional, not amplified —
# the hazard this analyzer hunts is a small request field exploding into
# a huge size (range/interval -> millions of windows), which never
# routes through len().  min() is handled separately: it launders only
# when some argument is itself clean (an actual cap); min of two
# request-derived values is still unbounded.
CLEAN_CALLS = {"isinstance", "hasattr", "id", "bool", "callable",
               "len"}
# attribute calls whose results are operator-controlled, not
# request-controlled: config getters and stats plumbing
CLEAN_ATTR_CALLS = {"get_int", "get_bool", "get_float", "get_string",
                    "get_properties", "record", "mark", "monotonic",
                    "time"}
PASSTHROUGH_CALLS = {"int", "float", "str", "abs", "max", "sorted",
                     "list", "tuple", "set", "dict", "sum", "round",
                     "getattr", "enumerate", "zip", "map", "filter",
                     "reversed"}

SINK_DIRS = ("opentsdb_tpu/ops/", "opentsdb_tpu/storage/",
             "opentsdb_tpu/query/", "opentsdb_tpu/parallel/",
             "opentsdb_tpu/histogram/", "opentsdb_tpu/expression/")

_MAX_FIXPOINT_ROUNDS = 8

RET_ORIGIN = ("r",)          # "return value is request-derived" marker


def _is_nested(fi) -> bool:
    return ".<nested>." in fi.qname


class _Summary:
    __slots__ = ("unsan_params", "return_labels", "return_types",
                 "param_types")

    def __init__(self):
        self.unsan_params: set[str] = set()
        self.return_labels: set = set()      # ("p", name) | RET_ORIGIN
        self.return_types: set[str] = set()
        self.param_types: dict[str, set[str]] = {}

    def snapshot(self):
        return (frozenset(self.unsan_params),
                frozenset(self.return_labels),
                frozenset(self.return_types),
                frozenset((k, frozenset(v))
                          for k, v in self.param_types.items()))


class _FnPass:
    """One analysis pass over a function body (nested defs inlined)."""

    def __init__(self, fi, graph, summaries, sink_dirs, final: bool,
                 src_by_path=None):
        self.fi = fi
        self.graph = graph
        self.summaries = summaries
        self.final = final
        self.src = (src_by_path or {}).get(fi.path)
        self.in_sink_file = fi.path.startswith(sink_dirs) or any(
            d in fi.path for d in sink_dirs)
        self.labels: dict[str, set] = {}
        self.types: dict[str, set[str]] = {}
        self.origins: dict = {}              # label -> (line, desc)
        self.findings: list[Finding] = []
        self.summary: _Summary = summaries[fi.qname]
        self.sanitizer_lines = self._collect_sanitizers()
        self._seed()

    # -- setup -----------------------------------------------------------

    def _seed(self) -> None:
        for p in self.fi.params:
            self.labels[p] = {("p", p)}
            ptypes = self.summary.param_types.get(p)
            if ptypes:
                self.types[p] = set(ptypes)
        for a in (self.fi.node.args.posonlyargs + self.fi.node.args.args
                  + self.fi.node.args.kwonlyargs):
            ann = a.annotation
            if isinstance(ann, ast.Name):
                self.types.setdefault(a.arg, set()).add(ann.id)
            elif isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                              str):
                self.types.setdefault(a.arg, set()).add(ann.value)
        if self.fi.name in TELNET_FUNCS:
            for p in self.fi.params:
                if p in TELNET_PARAMS:
                    lab = ("o", "telnet:" + p)
                    self.origins[lab] = (self.fi.node.lineno,
                                         "telnet request field %r" % p)
                    self.labels[p] = self.labels.get(p, set()) | {lab}

    def _collect_sanitizers(self) -> list[int]:
        lines = []
        for node in ast.walk(self.fi.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SANITIZER_CHARGE:
                lines.append(node.lineno)
            elif isinstance(node, ast.If) and self._is_limit_guard(node):
                lines.append(node.lineno)
        return sorted(lines)

    @staticmethod
    def _is_limit_guard(node: ast.If) -> bool:
        """`if <test mentioning get_*_limit(...)>: ... raise ...`"""
        has_getter = any(
            isinstance(c, ast.Call) and isinstance(c.func, ast.Attribute)
            and c.func.attr in SANITIZER_LIMIT_GETTERS
            for c in ast.walk(node.test))
        if not has_getter:
            return False
        return any(isinstance(s, ast.Raise)
                   for b in node.body for s in ast.walk(b))

    def _sanitized_before(self, line: int) -> bool:
        return any(s < line for s in self.sanitizer_lines)

    # -- label / type evaluation ----------------------------------------

    def eval_types(self, e) -> set[str]:
        if isinstance(e, ast.Name):
            return self.types.get(e.id, set())
        if isinstance(e, ast.IfExp):
            return self.eval_types(e.body) | self.eval_types(e.orelse)
        if isinstance(e, ast.Call):
            out: set[str] = set()
            for info, is_ctor, cls in self._resolve(e):
                if is_ctor and cls:
                    out.add(cls)
                elif info is not None and not _is_nested(info):
                    out |= self.summaries[info.qname].return_types
            return out
        return set()

    def _resolve(self, call: ast.Call):
        recv_types = None
        if isinstance(call.func, ast.Attribute):
            recv_types = self.eval_types(call.func.value)
        targets = self.graph.resolve(call, self.fi, recv_types=recv_types)
        return [(i, c, k) for i, c, k in targets
                if i is None or i.qname in self.summaries or c]

    def _map_args(self, call: ast.Call, info, is_ctor: bool):
        """[(param_name, arg_expr)] for a resolved target."""
        if info is None:
            return []
        params = info.params
        recv = None
        if isinstance(call.func, ast.Attribute) and info.is_method \
                and not is_ctor:
            recv = call.func.value
        out = []
        pos = iter(params)
        if params and params[0] == "self":
            next(pos, None)
            if recv is not None:
                out.append(("self", recv))
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                break
            p = next(pos, None)
            if p is None:
                break
            out.append((p, arg))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in params:
                out.append((kw.arg, kw.value))
        return out

    def eval_labels(self, e) -> set:
        if e is None:
            return set()
        if isinstance(e, ast.Name):
            return set(self.labels.get(e.id, ()))
        if isinstance(e, ast.Attribute):
            if e.attr.isupper():
                return set()     # CLASS_CONSTANT on a tainted object
            return self.eval_labels(e.value)
        if isinstance(e, ast.Subscript):
            return self.eval_labels(e.value)
        if isinstance(e, ast.Call):
            return self._eval_call(e)
        if isinstance(e, ast.BinOp):
            return self.eval_labels(e.left) | self.eval_labels(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.eval_labels(e.operand)
        if isinstance(e, ast.BoolOp):
            out = set()
            for v in e.values:
                out |= self.eval_labels(v)
            return out
        if isinstance(e, ast.IfExp):
            return self.eval_labels(e.body) | self.eval_labels(e.orelse)
        if isinstance(e, ast.Compare):
            return set()                      # booleans are not sizes
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for v in e.elts:
                out |= self.eval_labels(v)
            return out
        if isinstance(e, ast.Dict):
            out = set()
            for v in list(e.keys) + list(e.values):
                out |= self.eval_labels(v)
            return out
        if isinstance(e, ast.JoinedStr):
            out = set()
            for v in e.values:
                out |= self.eval_labels(v)
            return out
        if isinstance(e, ast.FormattedValue):
            return self.eval_labels(e.value)
        if isinstance(e, ast.Starred):
            return self.eval_labels(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            out = set()
            for gen in e.generators:
                out |= self.eval_labels(gen.iter)
            if isinstance(e, ast.DictComp):
                out |= self.eval_labels(e.key) | self.eval_labels(e.value)
            else:
                out |= self.eval_labels(e.elt)
            return out
        if isinstance(e, (ast.Constant, ast.Lambda)):
            return set()
        if isinstance(e, ast.NamedExpr):
            return self.eval_labels(e.value)
        if isinstance(e, ast.Slice):
            return (self.eval_labels(e.lower) | self.eval_labels(e.upper)
                    | self.eval_labels(e.step))
        return set()

    def _source_origin(self, call: ast.Call):
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        attr = f.attr
        if attr not in SOURCE_ATTRS and not any(
                attr.startswith(p) for p in SOURCE_ATTR_PREFIXES):
            return None
        arg = ""
        if call.args and isinstance(call.args[0], ast.Constant):
            arg = repr(call.args[0].value)
        return "%s(%s)" % (attr, arg)

    def _eval_call(self, call: ast.Call) -> set:
        desc = self._source_origin(call)
        if desc is not None:
            lab = ("o", "src:%s" % desc)
            self.origins[lab] = (call.lineno, "request field %s" % desc)
            return {lab}
        fname = call.func.id if isinstance(call.func, ast.Name) else None
        if fname in CLEAN_CALLS:
            return set()
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in CLEAN_ATTR_CALLS:
            return set()
        if fname == "min":
            # a clamp only if something actually bounds it: any
            # label-free argument caps the result; min() of exclusively
            # request-derived values stays unbounded
            per_arg = [self.eval_labels(a) for a in call.args]
            if len(per_arg) >= 2 and any(not labs for labs in per_arg):
                return set()
            return set().union(*per_arg) if per_arg else set()
        arg_labels = set()
        for a in call.args:
            arg_labels |= self.eval_labels(a)
        for kw in call.keywords:
            arg_labels |= self.eval_labels(kw.value)
        if fname in PASSTHROUGH_CALLS:
            return arg_labels
        targets = self._resolve(call)
        if not targets:
            # unresolved: a method on tainted data stays tainted
            # (text.split() of a tainted string); a method on an
            # UNtainted object selects store-resident data — the args
            # pick what to return, they don't make the result
            # attacker-sized — so argument taint does not pass through.
            # Free calls and module-alias calls keep arg passthrough.
            if isinstance(call.func, ast.Attribute):
                base = call.func.value
                mod = self.graph.modules.get(self.fi.module)
                if isinstance(base, ast.Name) and mod is not None \
                        and base.id in mod.imports:
                    return arg_labels
                return self.eval_labels(base)
            return arg_labels
        out = set()
        for info, is_ctor, cls in targets:
            if is_ctor:
                # tainted constructor args taint the instance
                out |= arg_labels
                if isinstance(call.func, ast.Attribute):
                    out |= self.eval_labels(call.func.value)
                continue
            if info is None or _is_nested(info):
                continue
            summ = self.summaries[info.qname]
            mapped = self._map_args(call, info, is_ctor)
            for lab in summ.return_labels:
                if lab == RET_ORIGIN:
                    nlab = ("o", "ret:%s" % info.qname)
                    self.origins[nlab] = (
                        call.lineno,
                        "request-derived result of %s()" % info.name)
                    out.add(nlab)
                elif lab[0] == "p":
                    for p, arg in mapped:
                        if p == lab[1]:
                            out |= self.eval_labels(arg)
        return out

    # -- statement walk --------------------------------------------------

    def run(self) -> None:
        self.emit = False
        for _ in range(_MAX_FIXPOINT_ROUNDS):
            before = {k: set(v) for k, v in self.labels.items()}
            tbefore = {k: set(v) for k, v in self.types.items()}
            self._walk(self.fi.node.body, while_labels=set())
            if before == self.labels and tbefore == self.types:
                break
        self.emit = self.final
        self._walk(self.fi.node.body, while_labels=set())
        self._update_summary()

    def _assign_name(self, name: str, labs: set, typs: set[str]) -> None:
        if labs:
            self.labels[name] = self.labels.get(name, set()) | labs
        if typs:
            self.types[name] = self.types.get(name, set()) | typs

    def _assign_target(self, tgt, labs: set, typs: set[str]) -> None:
        if isinstance(tgt, ast.Name):
            self._assign_name(tgt.id, labs, typs)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._assign_target(el, labs, set())
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            # storing tainted data INTO an object taints the object
            base = tgt.value
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and labs:
                self._assign_name(base.id, labs, set())
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, labs, set())

    def _walk(self, stmts, while_labels: set) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: inline — closures share this label env
                self._walk(st.body, while_labels)
                continue
            if isinstance(st, ast.ClassDef):
                continue
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = st.value
                if value is None:
                    continue
                labs = self.eval_labels(value) | while_labels
                typs = self.eval_types(value)
                self._check_expr_sinks(value)
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                # the `n = min(n, cap)` clamp idiom is a STRONG update:
                # the rebound name is laundered — but only when the cap
                # side is itself label-free (min of two request-derived
                # values bounds nothing).  Labels otherwise only ever
                # grow, which is what makes the fixpoint sound.
                if (len(targets) == 1 and isinstance(targets[0], ast.Name)
                        and isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)
                        and value.func.id == "min"
                        and any(isinstance(a, ast.Name)
                                and a.id == targets[0].id
                                for a in value.args)
                        and any(not self.eval_labels(a)
                                for a in value.args
                                if not (isinstance(a, ast.Name)
                                        and a.id == targets[0].id))):
                    self.labels[targets[0].id] = set()
                    continue
                for tgt in targets:
                    self._assign_target(tgt, labs, typs)
                continue
            if isinstance(st, ast.Expr):
                self.eval_labels(st.value)
                self._check_expr_sinks(st.value)
                continue
            if isinstance(st, ast.Return):
                if st.value is not None:
                    labs = self.eval_labels(st.value) | while_labels
                    self._note_return(labs, self.eval_types(st.value))
                    self._check_expr_sinks(st.value)
                continue
            if isinstance(st, ast.For):
                labs = self.eval_labels(st.iter) | while_labels
                self._assign_target(st.target, labs, set())
                self._check_loop_bound(st)
                self._check_expr_sinks(st.iter)
                self._walk(st.body, while_labels)
                self._walk(st.orelse, while_labels)
                continue
            if isinstance(st, ast.While):
                # control dependence: values computed under a tainted
                # loop condition are sized by it (the pad_pow2 idiom).
                # The condition is usually a Compare — whose VALUE is a
                # clean bool — so the labels come from its operands.
                cond = self._cond_labels(st.test)
                self._check_expr_sinks(st.test)
                self._walk(st.body, while_labels | cond)
                self._walk(st.orelse, while_labels)
                continue
            if isinstance(st, ast.If):
                self._check_expr_sinks(st.test)
                self._walk(st.body, while_labels)
                self._walk(st.orelse, while_labels)
                continue
            if isinstance(st, ast.With):
                for item in st.items:
                    labs = self.eval_labels(item.context_expr)
                    self._check_expr_sinks(item.context_expr)
                    if item.optional_vars is not None:
                        self._assign_target(item.optional_vars, labs,
                                            set())
                self._walk(st.body, while_labels)
                continue
            if isinstance(st, ast.Try):
                self._walk(st.body, while_labels)
                for h in st.handlers:
                    self._walk(h.body, while_labels)
                self._walk(st.orelse, while_labels)
                self._walk(st.finalbody, while_labels)
                continue
            if isinstance(st, (ast.Raise, ast.Assert)):
                continue
            # everything else (Pass, Break, Continue, Global, ...)

    def _cond_labels(self, e) -> set:
        out = set()
        for node in ast.walk(e):
            if isinstance(node, ast.Name):
                out |= set(self.labels.get(node.id, ()))
        return out

    def _note_return(self, labs: set, typs: set[str]) -> None:
        for lab in labs:
            if lab[0] == "p":
                self.summary.return_labels.add(lab)
            else:
                self.summary.return_labels.add(RET_ORIGIN)
        self.summary.return_types |= typs

    # -- sinks -----------------------------------------------------------

    def _alloc_size_labels(self, call: ast.Call):
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr in ALLOC_FUNCS
                and isinstance(f.value, ast.Name)
                and f.value.id in ALLOC_MODULES):
            return None
        size_exprs = []
        if f.attr == "arange":
            size_exprs = list(call.args)
        elif call.args:
            size_exprs = [call.args[0]]
        for kw in call.keywords:
            if kw.arg == "shape":
                size_exprs.append(kw.value)
        labs = set()
        for e in size_exprs:
            labs |= self.eval_labels(e)
        return ("%s.%s allocation" % (f.value.id, f.attr), labs,
                call.lineno)

    def _check_expr_sinks(self, e) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                if self.in_sink_file:
                    hit = self._alloc_size_labels(node)
                    if hit is not None:
                        self._sink(*hit)
                self._check_call_edge(node)
            elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                            ast.Mult) \
                    and self.in_sink_file:
                if isinstance(node.left, ast.List):
                    labs = self.eval_labels(node.right)
                    self._sink("list preallocation", labs, node.lineno)
                elif isinstance(node.right, ast.List):
                    labs = self.eval_labels(node.left)
                    self._sink("list preallocation", labs, node.lineno)

    def _check_loop_bound(self, st: ast.For) -> None:
        if not self.in_sink_file:
            return
        it = st.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range":
            labs = set()
            for a in it.args:
                labs |= self.eval_labels(a)
            self._sink("range() loop bound", labs, st.lineno)

    def _sink(self, what: str, labs: set, line: int) -> None:
        if not labs or self._sanitized_before(line):
            return
        if self.src is not None and self.src.suppressed(line, RULE_TAINT):
            # a justified suppression at the sink (e.g. "store-sized,
            # bounded by resident data") clears the whole route — the
            # summary must not keep poisoning callers
            return
        for lab in labs:
            if lab[0] == "p":
                self.summary.unsan_params.add(lab[1])
            elif getattr(self, "emit", False):
                oline, desc = self.origins.get(lab, (line, "request data"))
                related = ((self.fi.path, oline,
                            "tainted value originates here"),) \
                    if oline != line else ()
                self.findings.append(Finding(
                    self.fi.path, line, RULE_TAINT,
                    "%s in '%s' is sized by %s with no limits sanitizer "
                    "on the route — charge a QueryBudget or clamp "
                    "(min/limits.get_*_limit guard) before allocating"
                    % (what, self.fi.name, desc), related=related))

    def _check_call_edge(self, call: ast.Call) -> None:
        """Tainted arg passed to a callee whose param reaches a sink."""
        targets = self._resolve(call)
        if not targets:
            return
        for info, is_ctor, _cls in targets:
            if info is None or _is_nested(info):
                continue
            summ = self.summaries[info.qname]
            if not summ.unsan_params:
                continue
            for p, arg in self._map_args(call, info, is_ctor):
                if p not in summ.unsan_params:
                    continue
                labs = self.eval_labels(arg)
                if not labs or self._sanitized_before(call.lineno):
                    continue
                for lab in labs:
                    if lab[0] == "p":
                        self.summary.unsan_params.add(lab[1])
                    elif getattr(self, "emit", False):
                        oline, desc = self.origins.get(
                            lab, (call.lineno, "request data"))
                        related = []
                        if oline != call.lineno:
                            related.append(
                                (self.fi.path, oline,
                                 "tainted value originates here"))
                        related.append(
                            (info.path, info.node.lineno,
                             "unsanitized parameter '%s' of '%s'"
                             % (p, info.qname)))
                        self.findings.append(Finding(
                            self.fi.path, call.lineno, RULE_TAINT,
                            "%s flows from '%s' into '%s' parameter "
                            "'%s', which reaches an allocation-size/"
                            "loop-bound sink with no limits sanitizer "
                            "on the route — charge a QueryBudget or "
                            "clamp before the call"
                            % (desc, self.fi.name, info.name, p),
                            related=tuple(related)))

    def _propagate_param_types(self) -> None:
        for node in ast.walk(self.fi.node):
            if not isinstance(node, ast.Call):
                continue
            for info, is_ctor, _cls in self._resolve(node):
                if info is None or is_ctor or _is_nested(info):
                    continue
                summ = self.summaries[info.qname]
                for p, arg in self._map_args(node, info, is_ctor):
                    typs = self.eval_types(arg)
                    if typs:
                        summ.param_types.setdefault(p, set()).update(typs)

    def _update_summary(self) -> None:
        self._propagate_param_types()


def _analysis_functions(graph):
    return [fi for fi in graph.funcs.values() if not _is_nested(fi)]


def finish(ctx: LintContext) -> list[Finding]:
    graph = get_callgraph(ctx)
    bucket = ctx.bucket("taint")
    sink_dirs = tuple(bucket.get("sink_paths", SINK_DIRS))
    funcs = _analysis_functions(graph)
    src_by_path = {src.path: src for src in ctx.files}
    summaries = {fi.qname: _Summary() for fi in funcs}
    for _ in range(_MAX_FIXPOINT_ROUNDS):
        before = {q: s.snapshot() for q, s in summaries.items()}
        for fi in funcs:
            _FnPass(fi, graph, summaries, sink_dirs, final=False,
                    src_by_path=src_by_path).run()
        if before == {q: s.snapshot() for q, s in summaries.items()}:
            break
    findings: list[Finding] = []
    for fi in funcs:
        fp = _FnPass(fi, graph, summaries, sink_dirs, final=True,
                     src_by_path=src_by_path)
        fp.run()
        findings.extend(fp.findings)
    # dedupe identical (path, line, rule, message) — the emit walk can
    # visit an expression more than once
    return sorted(set(findings))


def check(src: SourceFile, ctx: LintContext) -> list[Finding]:
    return []


ANALYZER = Analyzer("taint", (RULE_TAINT,), check, finish)
