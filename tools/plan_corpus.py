"""Pin the planner's routing: explain a canonical query matrix into
PLAN_CORPUS.json.

Every entry explains one query (optionally under what-if overrides)
against a deterministic in-process TSDB profile and records the
routing verdict — path, plan fingerprint, and the full discrete
provenance (shapes, chosen kernel modes, lane/cache verdicts,
calibration layer; never raw milliseconds) — via the SAME
plan_decision() the executor dispatches on (query/plandecision.py).

The committed PLAN_CORPUS.json is byte-pinned by a tier-1 test
(tests/test_explain.py) exactly like the generated docs: any change to
planner routing — a new eligibility gate, a reordered consult, a
costmodel flip at a pinned shape — surfaces as a reviewed corpus diff
instead of a silent perf regression.

    python tools/plan_corpus.py                  # rewrite the corpus
    python tools/plan_corpus.py --out /tmp/x     # write elsewhere
    python tools/plan_corpus.py --check          # exit 1 on drift

Deterministic by construction: fixed epoch timestamps, fixed data,
CPU platform (run under JAX_PLATFORMS=cpu), no wall-clock reads in
any recorded field, sorted-key JSON.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

CORPUS_PATH = os.path.join(REPO, "PLAN_CORPUS.json")

BASE = 1_356_998_400            # seconds; fixed epoch, never now()

# One profile = one deterministic daemon config + seeded dataset.
# mesh stays off everywhere (no shard_map at HEAD).
_COMMON = {
    "tsd.core.auto_create_metrics": "true",
    "tsd.query.mesh.enable": "false",
    "tsd.rollup.interval": "0",          # no maintenance cadence races
    "tsd.stats.interval": "0",
    # the legacy profiles pin the PRE-batching routing matrix; the
    # `batched` arm gets its own profile below so every older entry's
    # path/fingerprint stays a stable regression anchor
    "tsd.query.batch.enable": "false",
}

PROFILES: dict[str, dict] = {
    "base": {
        "tsd.query.host_lane.max_points": "4096",
    },
    # the host-lane path needs the device cache OUT of the way: with it
    # on, a small cold query inline-builds an entry and serves resident
    # (pinned by resident_small_inline_build below)
    "hostlane": {
        "tsd.query.host_lane.max_points": "4096",
        "tsd.query.device_cache.enable": "false",
    },
    "streaming": {
        "tsd.query.streaming.point_threshold": "1000",
    },
    "tiled": {
        "tsd.query.streaming.point_threshold": "1000",
        "tsd.query.streaming.state_mb": "8",
    },
    "refused": {
        "tsd.query.streaming.point_threshold": "1000",
        "tsd.query.streaming.state_mb": "8",
        "tsd.query.spill.enable": "false",
    },
    "rollup": {
        "tsd.rollup.enable": "true",
        "tsd.rollup.intervals": "1m,1h",
        "tsd.query.degrade": "allow",
    },
    # fused multi-query dispatch (query/batcher.py): the `batched`
    # routing arm + its costmodel-priced dispatch-now decline, with
    # the device cache out of the way so the declined arm resolves
    # cleanly
    "batched": {
        "tsd.query.batch.enable": "true",
        "tsd.query.device_cache.enable": "false",
    },
}


def _feed(tsdb, metric: str, series: int, points: int,
          cadence_s: int) -> None:
    for h in range(series):
        tags = {"host": "h%02d" % h}
        for k in range(points):
            tsdb.add_point(metric, BASE + k * cadence_s,
                           float((k * 7 + h) % 101), tags)


def _build_profile(name: str):
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config
    props = dict(_COMMON)
    props.update(PROFILES[name])
    tsdb = TSDB(Config(props))
    if name == "base":
        _feed(tsdb, "corpus.small", 3, 64, 15)
        _feed(tsdb, "corpus.big", 4, 6000, 1)
    elif name == "hostlane":
        _feed(tsdb, "corpus.small", 3, 64, 15)
    elif name in ("streaming",):
        _feed(tsdb, "corpus.big", 4, 6000, 1)
    elif name in ("tiled", "refused"):
        _feed(tsdb, "corpus.wide", 8, 5760, 30)
    elif name == "batched":
        _feed(tsdb, "corpus.small", 3, 64, 15)
        _feed(tsdb, "corpus.big", 4, 6000, 1)
    elif name == "rollup":
        _feed(tsdb, "corpus.lane", 8, 5760, 15)
        # 7 days at 1m cadence: wide enough that a 60s-interval grid
        # ([8, 16384] padded) busts a 1 MB what-if budget -> the
        # striped lane serve engages
        _feed(tsdb, "corpus.lane7", 8, 10080, 60)
    return tsdb


def _warm_lanes(tsdb, m: str, start: int, end: int) -> None:
    """Consult (records demand) + build the demanded lane blocks —
    the tests' warm() idiom (tests/test_rollup_lanes.py)."""
    from opentsdb_tpu.models.tsquery import TSQuery, parse_m_subquery
    q = TSQuery(start=str(start), end=str(end),
                queries=[parse_m_subquery(m)])
    q.validate()
    tsdb.new_query_runner().run(q)
    for _ in range(40):
        if not tsdb.rollup_lanes.refresh(tsdb.store, max_blocks=256):
            break


# (name, profile, m, start, end, what_if, needs_warm_lanes)
ENTRIES = [
    ("host_lane_small", "hostlane", "sum:30s-avg:corpus.small",
     BASE, BASE + 64 * 15, {}, False),
    ("resident_small_inline_build", "base", "sum:30s-avg:corpus.small",
     BASE, BASE + 64 * 15, {}, False),
    ("resident_big", "base", "sum:30s-avg:corpus.big",
     BASE, BASE + 6000, {}, False),
    ("union_no_downsample", "base", "sum:corpus.small",
     BASE, BASE + 64 * 15, {}, False),
    ("agg_rewrite_whatif_warm", "base", "sum:30s-avg:corpus.big",
     BASE, BASE + 6000, {"assume_agg_cache": "warm"}, False),
    ("device_cache_whatif_cold", "base", "sum:30s-avg:corpus.big",
     BASE, BASE + 6000, {"assume_device_cache": "cold"}, False),
    # pins that costmodel what-ifs NEVER perturb the routing
    # fingerprint (must equal resident_big's)
    ("resident_big_forced_modes", "base", "sum:30s-avg:corpus.big",
     BASE, BASE + 6000,
     {"force_scan": "flat", "calibration": "default"}, False),
    ("rate_resident", "base", "sum:rate:30s-avg:corpus.big",
     BASE, BASE + 6000, {}, False),
    ("extreme_resident", "base", "max:30s-max:corpus.big",
     BASE, BASE + 6000, {}, False),
    ("streamed_big", "streaming", "sum:30s-avg:corpus.big",
     BASE, BASE + 6000, {}, False),
    ("tiled_wide", "tiled", "sum:1s-avg:corpus.wide",
     BASE, BASE + 5760 * 30, {}, False),
    ("refused_wide", "refused", "sum:1s-avg:corpus.wide",
     BASE, BASE + 5760 * 30, {}, False),
    ("rollup_lane_1m", "rollup", "sum:60s-sum:corpus.lane",
     BASE + 60, BASE + 5600 * 15, {}, True),
    ("rollup_lane_striped_whatif", "rollup",
     "sum:60s-sum:corpus.lane7", BASE + 60, BASE + 10080 * 60,
     {"assume_rollup": "warm", "state_mb": "1"}, False),
    ("degrade_preview", "rollup", "sum:15s-avg:corpus.lane",
     BASE, BASE + 5760 * 15, {"deadline_ms": "1"}, False),
    # fused multi-query dispatch: a dispatch-bound small query routes
    # through the batcher; a compute-heavy shape prices past the
    # amortize factor and DECLINES to dispatch-now (the cost-based
    # coalesce line, not a static batch size)
    ("batched_small", "batched", "sum:30s-avg:corpus.small",
     BASE, BASE + 64 * 15, {}, False),
    ("batched_declined_compute_bound", "batched",
     "sum:2s-avg:corpus.big", BASE, BASE + 6000, {}, False),
]


def build_corpus() -> dict:
    from opentsdb_tpu.models.tsquery import TSQuery, parse_m_subquery
    from opentsdb_tpu.query import explain as explain_mod

    corpus_entries = []
    tsdbs: dict[str, object] = {}
    try:
        for (name, profile, m, start, end, raw_wi, warm) in ENTRIES:
            tsdb = tsdbs.get(profile)
            if tsdb is None:
                tsdb = tsdbs[profile] = _build_profile(profile)
            if warm:
                _warm_lanes(tsdb, m, start, end)
            q = TSQuery(start=str(start), end=str(end),
                        queries=[parse_m_subquery(m)])
            q.validate()
            what_if = explain_mod.parse_what_if(raw_wi)
            report = explain_mod.explain_query(tsdb, q, what_if)
            segments = []
            for sub in report["subQueries"]:
                for seg in sub.get("segments", []):
                    rec = {"kind": seg["kind"], "path": seg["path"]}
                    if "fingerprint" in seg:
                        rec["fingerprint"] = seg["fingerprint"]
                        rec["provenance"] = seg["provenance"]
                    segments.append(rec)
            entry = {
                "name": name,
                "profile": profile,
                "query": m,
                "startOffsetS": start - BASE,
                "endOffsetS": end - BASE,
                "whatIf": report["whatIf"],
                "admission": {
                    "verdict": report["admission"]["verdict"],
                },
                "segments": segments,
            }
            degraded = report["admission"].get("degraded")
            if degraded is not None:
                entry["admission"]["degraded"] = degraded
            corpus_entries.append(entry)
    finally:
        for tsdb in tsdbs.values():
            tsdb.shutdown()
    return {
        "comment": ("Generated by tools/plan_corpus.py — byte-pinned "
                    "in tier-1 (tests/test_explain.py).  Regenerate "
                    "with: JAX_PLATFORMS=cpu python "
                    "tools/plan_corpus.py"),
        "entries": corpus_entries,
    }


def render(corpus: dict) -> str:
    return json.dumps(corpus, indent=2, sort_keys=True) + "\n"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=CORPUS_PATH)
    ap.add_argument("--check", action="store_true",
                    help="compare against the committed corpus; exit "
                         "1 on drift, write nothing")
    args = ap.parse_args()
    text = render(build_corpus())
    if args.check:
        try:
            with open(CORPUS_PATH, encoding="utf-8") as fh:
                committed = fh.read()
        except OSError:
            committed = ""
        if committed != text:
            sys.stderr.write(
                "PLAN_CORPUS.json is stale — planner routing changed; "
                "review the diff and regenerate with "
                "JAX_PLATFORMS=cpu python tools/plan_corpus.py\n")
            return 1
        print("PLAN_CORPUS.json is in sync")
        return 0
    with open(args.out, "w", encoding="utf-8") as fh:
        fh.write(text)
    print("wrote %s (%d entries)" % (args.out, len(ENTRIES)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
