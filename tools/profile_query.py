"""jax.profiler harness for the production query pipeline (VERDICT r2 #2),
rebased onto the EXPLAIN engine for its decision reporting.

Captures an XLA trace of the headline bench dispatch so the hot ops
(cumsum, searchsorted, gathers, segment reductions) can be attributed:

    python tools/profile_query.py [--outdir /tmp/tsdb_profile] [--passes 3]
    python tools/profile_query.py --what-if calibration=default \\
                                  --what-if force_scan=flat

Before tracing, the tool prints the per-axis kernel-strategy decision
for the bench shape — chosen mode, per-candidate predicted ms,
calibration layer — through the SAME decision path the planner and
/api/query/explain consult (obs.jaxprof.segment_decisions + the
explain engine's what-if repricer; no parallel re-implementation of
the planner's choosers lives here).  ``--what-if KEY=VAL`` accepts the
explain grammar's costmodel keys (``platform``, ``calibration``,
``force_search/scan/extreme/group``) and prints the repriced view
beside the live one.

View traces with TensorBoard's profile plugin or xprof.  Each profiled
pass uses a unique window origin and ends in a host drain (same honesty
rules as bench.py — `block_until_ready` does not wait on this platform,
so traces bounded by it would be empty).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def _decision_lines(what_if) -> list[str]:
    """The bench shape's strategy decisions via the shared explain
    path: one line per axis, live pricing first, the what-if repriced
    view appended when overrides are active."""
    from bench import GROUPS, INTERVAL_MS, N, S, START, STEP_MEAN_MS
    from opentsdb_tpu.obs import jaxprof
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.hostlane import execution_platform
    from opentsdb_tpu.query.explain import _reprice_decisions

    end = START + N * STEP_MEAN_MS + 5_000
    wp = pad_pow2(FixedWindows.for_range(START, end, INTERVAL_MS).count)
    g_dec = pad_pow2(GROUPS)
    platform = what_if.platform or execution_platform()
    decisions = jaxprof.segment_decisions(platform, S, N, wp, g_dec,
                                          "avg", aggregator="sum")
    whatif = _reprice_decisions(decisions, what_if, S, N, wp, g_dec,
                                platform)

    def fmt(tag: str, axis: str, rep: dict) -> str:
        cands = ", ".join("%s=%.3fms" % (m, ms)
                          for m, ms in sorted(rep["candidates"].items()))
        return ("%s %s: mode=%s source=%s calibration=%s [%s]"
                % (tag, axis, rep["mode"], rep["source"],
                   rep["calibration"], cands))

    lines = [fmt("decision", axis, rep)
             for axis, rep in decisions.items()]
    if whatif is not None:
        lines.extend(fmt("what-if ", axis, rep)
                     for axis, rep in whatif.items())
    return lines


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="/tmp/tsdb_profile")
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--what-if", action="append", default=[],
                    metavar="KEY=VAL",
                    help="explain-grammar costmodel override "
                         "(platform=, calibration=, force_<axis>=); "
                         "repeatable")
    ap.add_argument("--decisions-only", action="store_true",
                    help="print the strategy decisions and exit "
                         "without tracing")
    args = ap.parse_args()

    from opentsdb_tpu.query.explain import WhatIfError, parse_what_if
    raw = {}
    for spec in args.what_if:
        if "=" not in spec:
            ap.error("--what-if takes KEY=VAL, got %r" % spec)
        k, v = spec.split("=", 1)
        raw[k.strip()] = v
    try:
        what_if = parse_what_if(raw)
    except WhatIfError as e:
        ap.error(str(e))

    from bench import _note
    for line in _decision_lines(what_if):
        _note(line)
    if args.decisions_only:
        return

    import jax
    from bench import (_OriginSequence, build_spec, dispatch, drain,
                       make_batch)

    batch = make_batch()
    spec, wargs, g_pad = build_spec()
    origins = _OriginSequence()
    drain(dispatch(spec, g_pad, batch, wargs, origins.next()))  # compile
    _note("compiled; tracing %d passes -> %s" % (args.passes, args.outdir))

    with jax.profiler.trace(args.outdir):
        for _ in range(args.passes):
            out = dispatch(spec, g_pad, batch, wargs, origins.next())
            drain(out)
    _note("trace written to %s" % args.outdir)


if __name__ == "__main__":
    main()
