"""jax.profiler harness for the production query pipeline (VERDICT r2 #2).

Captures an XLA trace of the headline bench dispatch so the hot ops
(cumsum, searchsorted, gathers, segment reductions) can be attributed:

    python tools/profile_query.py [--outdir /tmp/tsdb_profile] [--passes 3]

View with TensorBoard's profile plugin or xprof.  Each profiled pass uses
a unique window origin and ends in a host drain (same honesty rules as
bench.py — `block_until_ready` does not wait on this platform, so traces
bounded by it would be empty).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="/tmp/tsdb_profile")
    ap.add_argument("--passes", type=int, default=3)
    args = ap.parse_args()

    import jax
    from bench import (_OriginSequence, build_spec, dispatch, drain,
                       make_batch, _note)

    batch = make_batch()
    spec, wargs, g_pad = build_spec()
    origins = _OriginSequence()
    drain(dispatch(spec, g_pad, batch, wargs, origins.next()))  # compile
    _note("compiled; tracing %d passes -> %s" % (args.passes, args.outdir))

    with jax.profiler.trace(args.outdir):
        for _ in range(args.passes):
            out = dispatch(spec, g_pad, batch, wargs, origins.next())
            drain(out)
    _note("trace written to %s" % args.outdir)


if __name__ == "__main__":
    main()
