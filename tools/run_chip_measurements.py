"""One-shot real-chip measurement session for round 3 artifacts.

Runs, in order, each as a separate subprocess (the axon tunnel is
exclusive and can wedge if a JAX process dies mid-dispatch — isolating
stages means a crash loses one stage, not the session):

  1. bench_prefix.py          — A/B the hot-path variants (JSON lines)
  2. bench.py                 — headline number with the winning defaults
  3. bench_configs.py         — BASELINE configs 1-7 at full scale

Results append to BENCH_CONFIGS_r03.json (JSON lines + a trailing
metadata line).  Run: python tools/run_chip_measurements.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "BENCH_CONFIGS_r03.json")


def run_stage(name: str, argv: list[str], timeout: int) -> list[str]:
    print("== %s ==" % name, file=sys.stderr, flush=True)
    t0 = time.time()
    proc = subprocess.run(argv, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout)
    sys.stderr.write(proc.stderr[-4000:])
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    print("== %s done rc=%d in %.0fs, %d json lines =="
          % (name, proc.returncode, time.time() - t0, len(lines)),
          file=sys.stderr, flush=True)
    return lines


def main() -> None:
    results: list[dict] = []
    stages = [
        ("bench_prefix", [sys.executable, "bench_prefix.py"], 3600),
        ("stage_bench", [sys.executable, "tools/stage_bench.py"], 3600),
        ("bench", [sys.executable, "bench.py"], 1800),
    ]
    # One subprocess PER config: config 2 crashed the TPU worker in the r3
    # session and the single bench_configs process lost configs 3-7 with it.
    # Isolated, a crash costs exactly one config (the worker restarts
    # between subprocesses).
    stages += [("bench_configs:%d" % c,
                [sys.executable, "bench_configs.py", "--config", str(c)],
                2400) for c in range(1, 8)]
    for name, argv, timeout in stages:
        try:
            for ln in run_stage(name, argv, timeout):
                rec = json.loads(ln)
                rec["stage"] = name
                results.append(rec)
        except Exception as e:          # keep later stages alive
            print("stage %s failed: %s" % (name, e), file=sys.stderr)
            results.append({"stage": name, "error": str(e)})

    with open(OUT, "w") as fh:
        for rec in results:
            fh.write(json.dumps(rec) + "\n")
        fh.write(json.dumps({
            "stage": "meta",
            "recorded_unix": int(time.time()),
            "methodology": "drain-synced (block_until_ready is a no-op on "
                           "axon), unique operands per dispatch, RTT "
                           "subtracted, >=1s wall per measurement; see "
                           "bench.py docstring",
        }) + "\n")
    print("wrote %s (%d records)" % (OUT, len(results)))


if __name__ == "__main__":
    main()
