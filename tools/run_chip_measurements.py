"""One-shot real-chip measurement session for round 5 artifacts.

Runs, in PRIORITY order for a late tunnel recovery, each as a separate
subprocess (the axon tunnel is exclusive and can wedge if a JAX process
dies mid-dispatch — isolating stages means a crash loses one stage, not
the session):

  1. bench.py                 — headline number (BENCH_WINNERS.json
                                chip-crowned defaults)
  2. bench_configs.py         — BASELINE configs 1-7 at full scale,
                                crash-isolated one subprocess per config,
                                each under a COOPERATIVE in-process
                                deadline (--deadline) that finalizes a
                                partial-but-honest row; the subprocess
                                timeout sits 900s behind it as a last
                                resort (its SIGKILL mid-dispatch is what
                                wedged the tunnel in both r4 sessions)
  3. tools/hist_bench.py      — histogram device-path throughput row
  4. bench_prefix.py          — A/B the hot-path variants (incl. the r5
                                subblock2 rows and the cost model's own
                                "auto" row); winners feed later stages
  5. tools/stage_bench.py     — per-stage attribution + the cost-model
                                calibration record

Results append to BENCH_CONFIGS_r05.json (JSON lines + a trailing
metadata line).  Run: python tools/run_chip_measurements.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "BENCH_CONFIGS_r05.json")

# Cooperative per-config budget; the subprocess SIGKILL fires 900s later
# (watchdog grace is 300s, so a healthy-but-slow config always finalizes
# its own row first).
CONFIG_DEADLINE_S = 1500

# Stage ORDER for a late tunnel recovery: the headline bench and the
# BASELINE configs come before the race/attribution stages, so a session
# cut short by the round boundary still produces the table the round is
# for (module-level so the priority test exercises THIS dict).
STAGE_PRIORITY = {"bench": 0, "bench_configs": 1, "hist_bench": 2,
                  "bench_prefix": 3, "stage_bench": 4, "profile": 5}


def run_stage(name: str, argv: list[str], timeout: int,
              extra_env: dict | None = None) -> tuple[list[str], int]:
    print("== %s ==" % name, file=sys.stderr, flush=True)
    t0 = time.time()
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.run(argv, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout, env=env)
    sys.stderr.write(proc.stderr[-4000:])
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    print("== %s done rc=%d in %.0fs, %d json lines =="
          % (name, proc.returncode, time.time() - t0, len(lines)),
          file=sys.stderr, flush=True)
    return lines, proc.returncode


def tunnel_alive(timeout: int = 240) -> bool:
    """Post-failure triage probe: can a fresh process still reach the
    chip?  Only called AFTER a stage failed (the tunnel is already
    suspect) — probing a healthy tunnel risks the kill-mid-dial wedge,
    so this is never a pre-flight check.  A wedged tunnel hangs the
    probe; the timeout kill classifies it dead."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; jax.devices(); "
             "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
             "print('TUNNEL_OK')"],
            capture_output=True, text=True, timeout=timeout)
        return "TUNNEL_OK" in probe.stdout
    except subprocess.TimeoutExpired:
        return False


def pick_winners(prefix_records: list[dict]) -> dict:
    """A/B winners from bench_prefix -> env overrides for later stages.

    Only the HONEST defaults race: the f32 config is excluded (it breaks
    the Java-double contract and never becomes a default); the min/max
    extreme A/B picks from its own pair.
    """
    env = {}
    by_cfg = {r["config"]: r["s_per_dispatch"] for r in prefix_records
              if "config" in r and "s_per_dispatch" in r}

    # Every candidate row is a COMPLETE measured configuration
    # (scan, search, group) — the winner is the fastest row actually
    # timed on the chip, never an unmeasured composition of per-axis
    # winners (fusion can interact; the combo row exists precisely so a
    # subblock+hier+sorted regression would disqualify itself here).
    # int64 / f32 rows are evidence-only: int32 compaction is the
    # baked default and f32 breaks the Java-double contract.
    full_rows = {
        "flat+int32": ("flat", "scan", "segment"),
        "blocked+int32": ("blocked", "scan", "segment"),
        "subblock+int32": ("subblock", "scan", "segment"),
        "subblock2+int32": ("subblock2", "scan", "segment"),
        "subblock2+int32+hier+sorted": ("subblock2", "hier", "sorted"),
        "flat+int32+search_scan": ("flat", "scan", "segment"),
        "flat+int32+search_compare_all": ("flat", "compare_all", "segment"),
        "flat+int32+search_hier": ("flat", "hier", "segment"),
        "flat+int32+group_segment": ("flat", "scan", "segment"),
        "flat+int32+group_matmul": ("flat", "scan", "matmul"),
        "flat+int32+group_sorted": ("flat", "scan", "sorted"),
        "flat+int32+group_sorted2": ("flat", "scan", "sorted2"),
        "subblock+int32+hier": ("subblock", "hier", "segment"),
        "subblock+int32+sorted": ("subblock", "scan", "sorted"),
        "flat+int32+hier+sorted": ("flat", "hier", "sorted"),
        "subblock+int32+hier+sorted": ("subblock", "hier", "sorted"),
        "subblock+int32+hier+sorted2": ("subblock", "hier", "sorted2"),
        "subblock2+int32+hier+sorted2": ("subblock2", "hier", "sorted2"),
    }
    timed = [(by_cfg[c], modes) for c, modes in full_rows.items()
             if c in by_cfg]
    if timed:
        _, (scan, search, group) = min(timed)
        env["TSDB_SCAN_MODE"] = scan
        env["TSDB_SEARCH_MODE"] = search
        env["TSDB_GROUP_REDUCE_MODE"] = group
    ext_modes = ("scan", "segment", "subblock")
    ext = [(by_cfg["min+extreme_" + m], m) for m in ext_modes
           if "min+extreme_" + m in by_cfg]
    if len(ext) == len(ext_modes):   # a partial race crowns no winner
        env["TSDB_EXTREME_MODE"] = min(ext)[1]
    if env:
        print("== A/B winners -> %s ==" % env, file=sys.stderr, flush=True)
        # Persist for bench.py's standalone runs (the driver invokes it
        # without this session's env): latest chip-crowned modes win.
        with open(os.path.join(REPO, "BENCH_WINNERS.json"), "w") as fh:
            json.dump({"env": env, "recorded_unix": int(time.time()),
                       "source": "bench_prefix A/B on the real chip "
                                 "(fastest complete measured config)"},
                      fh, indent=1)
    return env


def persist_calibration(stage_recs: list[dict], repo: str) -> bool:
    """Write stage_bench's chip-derived cost-model constants to
    BENCH_CALIBRATION.json (ops/costmodel.py reads it).  Returns True
    when a calibration record was found and written."""
    for rec in stage_recs:
        if rec.get("label") == "calibration" and rec.get("costs_tpu"):
            with open(os.path.join(repo, "BENCH_CALIBRATION.json"),
                      "w") as fh:
                json.dump({"tpu": rec["costs_tpu"]}, fh, indent=1)
            return True
    return False


def stage_overrides(name: str, winner_env: dict) -> dict:
    """Which env overrides a stage runs under.  The crowned winner env
    was measured at the HEADLINE shape and feeds the stages that
    dispatch that shape (stage_bench, bench, profile).  The BASELINE
    configs span very different shapes and run under the shape-driven
    cost model's auto selection — globally-forced winners are exactly
    what broke config 1 in r4 (hier cell blowup rc=1)."""
    if name.startswith("bench_configs") or name == "hist_bench":
        return {}
    return winner_env


def pick_stream_ratio(stage_recs: list[dict]) -> str | None:
    """Stream-chunk routing race (stage_bench, config-2 slice shape,
    W ~ 1.25N): when the dense edge-search fold beat the segment scatter
    on the chip, return the raised W/N routing threshold (as the env
    string) so config 2's sliced folds take the dense form; None keeps
    the module default.  A partial race (either row missing/errored)
    crowns nothing."""
    by_label = {r.get("label"): r.get("seconds") for r in stage_recs}
    seg = by_label.get("stream_chunk_segment")
    dense = by_label.get("stream_chunk_dense")
    if seg is not None and dense is not None and dense < seg:
        return "2.0"
    return None


def main() -> None:
    results: list[dict] = []
    stages = [
        ("bench_prefix", [sys.executable, "bench_prefix.py"], 3600),
        ("stage_bench", [sys.executable, "tools/stage_bench.py"], 3600),
        ("bench", [sys.executable, "bench.py"], 1800),
    ]
    # One subprocess PER config: config 2 crashed the TPU worker in the r3
    # session and the single bench_configs process lost configs 3-7 with it.
    # Isolated, a crash costs exactly one config (the worker restarts
    # between subprocesses).
    stages += [("bench_configs:%d" % c,
                [sys.executable, "bench_configs.py", "--config", str(c),
                 "--deadline", str(CONFIG_DEADLINE_S)],
                CONFIG_DEADLINE_S + 900) for c in range(1, 8)]
    # histogram device-path throughput (VERDICT r4 #9: first chip number
    # for the histogram query path)
    stages += [("hist_bench", [sys.executable, "tools/hist_bench.py"],
                1800)]
    # last (least critical): an XLA trace of the headline dispatch under
    # the crowned modes, for offline per-op attribution (untracked dir)
    stages += [("profile",
                [sys.executable, "tools/profile_query.py",
                 "--outdir", os.path.join(REPO, "PROFILE_r05"),
                 "--passes", "2"], 1200)]
    winner_env: dict = {}
    def write_out() -> None:
        # Rewritten after EVERY stage: a session cutoff (or a second
        # tunnel death) mid-run keeps everything measured so far.
        with open(OUT, "w") as fh:
            for rec in results:
                fh.write(json.dumps(rec) + "\n")
            fh.write(json.dumps({
                "stage": "meta",
                "recorded_unix": int(time.time()),
                "methodology": "drain-synced (block_until_ready is a "
                               "no-op on axon), unique operands per "
                               "dispatch, RTT subtracted, >=1s wall per "
                               "measurement; see bench.py docstring",
            }) + "\n")

    # STAGE_PRIORITY (module top): bench.py uses the prior-crowned
    # BENCH_WINNERS.json defaults (env overrides only appear once
    # bench_prefix has run); the configs run under cost-model auto by
    # design either way.
    stages.sort(key=lambda st: STAGE_PRIORITY.get(st[0].split(":")[0], 9))

    dead = False
    for name, argv, timeout in stages:
        if dead:
            results.append({"stage": name, "error":
                            "skipped: tunnel dead (post-failure probe)"})
            write_out()
            continue
        failed = False
        stage_env = stage_overrides(name, winner_env)
        try:
            lines, rc = run_stage(name, argv, timeout,
                                  extra_env=stage_env)
            failed = rc != 0
            stage_recs = []
            for ln in lines:
                rec = json.loads(ln)
                # stage_bench emits its own per-record "stage" label;
                # preserve it (the r04b session clobbered the attribution
                # labels and they had to be recovered from stderr)
                if "stage" in rec:
                    rec["label"] = rec.pop("stage")
                rec["stage"] = name
                if stage_env:
                    rec["ab_overrides"] = dict(stage_env)
                results.append(rec)
                stage_recs.append(rec)
            if name == "bench_prefix":
                winner_env = pick_winners(stage_recs)
            if name == "stage_bench":
                # persist the chip-derived cost-model constants so mode
                # auto-selection (ops/costmodel.py) follows THIS chip
                if persist_calibration(stage_recs, REPO):
                    print("== wrote BENCH_CALIBRATION.json ==",
                          file=sys.stderr, flush=True)
                ratio = pick_stream_ratio(stage_recs)
                if ratio is not None:
                    winner_env["TSDB_STREAM_SEGMENT_RATIO"] = ratio
                    print("== stream routing: dense won -> ratio %s =="
                          % ratio, file=sys.stderr, flush=True)
                    try:
                        with open(os.path.join(REPO,
                                               "BENCH_WINNERS.json")) as fh:
                            winners = json.load(fh)
                    except (OSError, ValueError):
                        winners = {"env": {}}
                    winners.setdefault("env", {})[
                        "TSDB_STREAM_SEGMENT_RATIO"] = ratio
                    with open(os.path.join(REPO,
                                           "BENCH_WINNERS.json"),
                              "w") as fh:
                        json.dump(winners, fh, indent=1)
        except Exception as e:          # keep later stages alive
            print("stage %s failed: %s" % (name, e), file=sys.stderr)
            results.append({"stage": name, "error": str(e)})
            failed = True
        write_out()
        if failed:
            # a failed stage means the tunnel is suspect: one triage
            # probe decides whether the remaining stages get their shot
            # or the session finalizes now instead of burning each
            # stage's full timeout against a wedge (configs 5-7 lost
            # ~75min to exactly that in the r04b session)
            if not tunnel_alive():
                print("== tunnel probe DEAD after %s: skipping remaining "
                      "stages ==" % name, file=sys.stderr, flush=True)
                dead = True
    print("wrote %s (%d records)" % (OUT, len(results)))


if __name__ == "__main__":
    main()
