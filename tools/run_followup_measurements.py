"""Follow-up chip session for the stages the r05 session lost.

The r05 session recorded the headline (518M dp/s, 8.3x), configs 1-3,
and an error row for config 5 before its rollup dispatch wedged the
tunnel (BENCH_CONFIGS_r05.json); config 6 was measured host-side after
the fact.  This runner, armed on the next tunnel recovery, covers the
rest — reusing run_chip_measurements' stage machinery — in priority
order for ANOTHER late recovery:

  1. bench.py              — headline under the int32-scan fix and the
                             rows_sorted permute skip (r4-crowned modes)
  2. bench_configs:4       — rate+p99/500M: first-ever number; the r05
                             failure was the int64 u32-pair XLA compile
                             bug the int32 index fix removes
  3. bench_configs:2 x2    — the streamed multi-agg config raced under
                             both chunk routings: dense edge-search
                             (TSDB_STREAM_SEGMENT_RATIO=2, hypothesis:
                             TPU scatters serialize) vs the segment
                             default that measured 0.034x in r05
  4. bench_configs:7       — p50 /api/query latency @1B pts (north star)
  5. bench_configs:5       — the rollup config that wedged r05, retried
                             LAST of the configs with its new progress
                             notes so a repeat hang is attributable and
                             costs nothing else
  6. bench_configs:1       — int32-fix validation (compile bug row)
  7. hist_bench            — histogram device-path row
  8. bench_prefix          — mode races incl. the r5 sorted2 rows;
                             crowns BENCH_WINNERS.json
  9. bench.py (crowned)    — headline under freshly crowned winners
 10. stage_bench           — attribution + calibration + stream rows
 11. profile

Rows append to BENCH_CONFIGS_r05b.json; measured rows then supersede
matching error/absent stages in BENCH_CONFIGS_r05.json (the canonical
artifact) — a value row is never replaced by an error row.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_chip_measurements import (  # noqa: E402
    CONFIG_DEADLINE_S, REPO, persist_calibration, pick_stream_ratio,
    pick_winners, run_stage, tunnel_alive)

OUT = os.path.join(REPO, "BENCH_CONFIGS_r05b.json")
CANON = os.path.join(REPO, "BENCH_CONFIGS_r05.json")
# Stages completed across watcher attempts (tunnel windows are short —
# the Aug 2 window lasted one stage): a retry resumes at the first stage
# the previous attempt lost instead of re-measuring from the top.
DONE_STATE = "/tmp/chip_followup.done"
# Headroom on top of a stage's own run_stage timeout when deciding
# whether it still fits before SESSION_DEADLINE_UNIX: result merge +
# state write + process teardown.
STAGE_WALL_MARGIN_S = 120


def _load_done() -> set:
    try:
        with open(DONE_STATE) as fh:
            return set(json.load(fh))
    except (OSError, ValueError):
        return set()


def _save_done(done: set) -> None:
    with open(DONE_STATE, "w") as fh:
        json.dump(sorted(done), fh)


def _superseded_chain(row: dict) -> list[dict]:
    """A row's supersede history as a list (older dict-form included)."""
    hist = row.get("superseded")
    if hist is None:
        return []
    return hist if isinstance(hist, list) else [hist]


def _in_superseded_chain(row: dict, rec: dict) -> bool:
    """True when rec's (value, vs_baseline) already appears in row's
    superseded history — re-merging it would alternate-supersede the
    current value forever (ADVICE r5 medium)."""
    key = (rec.get("value"), rec.get("vs_baseline"))
    return any((h.get("value"), h.get("vs_baseline")) == key
               for h in _superseded_chain(row))


def merge_into_canonical(results: list[dict]) -> None:
    """Fold measured rows into BENCH_CONFIGS_r05.json: a value row
    supersedes an error/absent row for the same stage; a fresh value row
    supersedes an older one (newer code), keeping the old value in
    "superseded".  Error rows never displace values, skip artifacts
    (bench.py "skipped": true, value 0.0) never displace REAL values,
    and a row already present in the superseded chain never re-merges
    (it is history, not news)."""
    try:
        with open(CANON) as fh:
            canon = [json.loads(ln) for ln in fh if ln.strip()]
    except OSError:
        canon = []
    meta = [r for r in canon if r.get("stage") == "meta"]
    rows = {r.get("stage"): r for r in canon if r.get("stage") != "meta"}
    order = [r.get("stage") for r in canon if r.get("stage") != "meta"]
    for rec in results:
        stage = rec.get("stage")
        if stage is None or "value" not in rec:
            continue
        prev = rows.get(stage)
        if prev is not None and "value" in prev:
            if rec.get("skipped") and not prev.get("skipped"):
                # a no-measurement artifact must never displace a real
                # number (ADVICE r5 high)
                continue
            if (prev.get("value") == rec.get("value")
                    and prev.get("vs_baseline") == rec.get("vs_baseline")):
                # Same record re-merged (write_out runs after every
                # stage): keep prev and its superseded history intact.
                continue
            if _in_superseded_chain(prev, rec):
                continue
            rec = dict(rec)
            # Chain the full history: a second supersede (e.g. the
            # crowned bench over the baseline bench) must not erase the
            # prior session's number.  Older dict-form entries migrate
            # to the list form on the next merge.  Skip artifacts carry
            # no measurement, so they never enter the history.
            hist = _superseded_chain(prev)
            if not prev.get("skipped"):
                hist = [{k: prev[k] for k in ("value", "vs_baseline")
                         if k in prev}] + hist
            if hist:
                rec["superseded"] = hist
        rows[stage] = rec
        if stage not in order:
            order.append(stage)
    with open(CANON, "w") as fh:
        for stage in order:
            fh.write(json.dumps(rows[stage]) + "\n")
        for r in meta:
            fh.write(json.dumps(r) + "\n")


def main() -> None:
    results: list[dict] = []
    py = sys.executable
    cfg = lambda n, env=None, tag="": (  # noqa: E731
        "bench_configs:%d%s" % (n, tag),
        [py, "bench_configs.py", "--config", str(n),
         "--deadline", str(CONFIG_DEADLINE_S)],
        CONFIG_DEADLINE_S + 900, env or {})
    stages = [
        ("bench", [py, "bench.py"], 1800, {}),
        cfg(4),
        cfg(2, {"TSDB_STREAM_SEGMENT_RATIO": "2.0"}, ":dense"),
        cfg(2, tag=":segment"),
        cfg(7),
        cfg(5),
        cfg(1),
        ("hist_bench", [py, "tools/hist_bench.py"], 1800, {}),
        ("bench_prefix", [py, "bench_prefix.py"], 3600, {}),
        # same stage name as the first run on purpose: bench.py reads
        # the freshly crowned BENCH_WINNERS.json itself, so this IS the
        # headline under production defaults — the merge supersedes the
        # earlier row and keeps it in "superseded"
        ("bench", [py, "bench.py"], 1800, "WINNERS"),
        ("stage_bench", [py, "tools/stage_bench.py"], 3600, {}),
        ("profile", [py, "tools/profile_query.py", "--outdir",
                     os.path.join(REPO, "PROFILE_r05"), "--passes", "2"],
         1200, "WINNERS"),
    ]

    winner_env: dict = {}

    def write_out() -> None:
        with open(OUT, "w") as fh:
            for rec in results:
                fh.write(json.dumps(rec) + "\n")
            fh.write(json.dumps({
                "stage": "meta", "recorded_unix": int(time.time()),
                "methodology": "see BENCH_CONFIGS_r05.json meta; "
                               "follow-up session (r05b)"}) + "\n")
        merge_into_canonical(results)

    done = _load_done()
    # Re-seed this attempt's OUT with the prior attempts' measured rows
    # for done stages, so r05b stays the union of the session's attempts
    # rather than truncating to the latest one.  Rows the canonical
    # artifact already remembers in a superseded chain stay OUT of the
    # re-seed: merging one back would displace the newer current value,
    # and the next resume would displace it back — the alternating
    # duplicate growth of ADVICE r5.  Skip artifacts never re-seed
    # (they are no-measurements awaiting a retry).
    done_names = {k.split(":", 1)[1] for k in done}
    canon_rows: dict = {}
    try:
        with open(CANON) as fh:
            for ln in fh:
                row = json.loads(ln)
                if row.get("stage") not in (None, "meta"):
                    canon_rows[row["stage"]] = row
    except (OSError, ValueError):
        pass
    try:
        with open(OUT) as fh:
            for ln in fh:
                rec = json.loads(ln)
                if ("value" in rec and rec.get("stage") in done_names
                        and not rec.get("skipped")
                        and not _in_superseded_chain(
                            canon_rows.get(rec["stage"], {}), rec)):
                    results.append(rec)
    except (OSError, ValueError):
        pass
    # bench_prefix crowned winners in a prior attempt: rehydrate the env
    # for this attempt's "WINNERS" stages (profile / crowned bench).
    if any(k.endswith(":bench_prefix") for k in done):
        try:
            with open(os.path.join(REPO, "BENCH_WINNERS.json")) as fh:
                winner_env = dict(json.load(fh).get("env", {}))
        except (OSError, ValueError):
            pass
    dead = False
    any_failed = False
    for idx, (name, argv, timeout, env) in enumerate(stages):
        # Key by position, not name: the two "bench" entries (initial vs
        # freshly-crowned) are distinct runs that merge under one stage.
        # A done LATER entry of the same name also retires this one —
        # re-running the baseline bench after the crowned bench already
        # measured would supersede the crowned headline in the merge.
        done_key = "%d:%s" % (idx, name)
        if done_key in done or any(
                k.split(":", 1)[1] == name and int(k.split(":", 1)[0]) > idx
                for k in done):
            print("== %s already measured (prior attempt); skipping =="
                  % name, file=sys.stderr, flush=True)
            continue
        # Cooperative session budget (tpu_watch.sh): stop STARTING
        # stages near the wall deadline instead of being SIGKILLed
        # mid-dispatch — that kill is the known tunnel-wedge mechanism.
        # Gated on THIS stage's own run_stage timeout plus margin, not a
        # flat 600s (ADVICE r5 low): a 3600s bench_prefix started 900s
        # before the wall passes a flat check and then dies to the outer
        # watchdog mid-dispatch; a 1200s profile in the same window is
        # perfectly safe to start.
        wall_deadline = float(os.environ.get("SESSION_DEADLINE_UNIX", 0))
        stage_budget = timeout + STAGE_WALL_MARGIN_S
        if wall_deadline and time.time() > wall_deadline - stage_budget:
            results.append({"stage": name, "error":
                            "skipped: session wall budget exhausted "
                            "(stage needs %ds + %ds margin)"
                            % (timeout, STAGE_WALL_MARGIN_S)})
            any_failed = True
            write_out()
            continue
        if dead:
            results.append({"stage": name, "error":
                            "skipped: tunnel dead (post-failure probe)"})
            write_out()
            continue
        # "WINNERS" = apply bench_prefix's freshly crowned env; the
        # BASELINE configs run under cost-model auto by design, and the
        # explicit ratio race carries its own env
        stage_env = dict(winner_env) if env == "WINNERS" else dict(env)
        failed = False
        try:
            lines, rc = run_stage(name, argv, timeout, extra_env=stage_env)
            failed = rc != 0
            stage_recs = []
            for ln in lines:
                rec = json.loads(ln)
                if "stage" in rec:
                    rec["label"] = rec.pop("stage")
                # the two config-2 rows must not collide in the merge:
                # the stage key carries the routing tag
                rec["stage"] = name
                if stage_env:
                    rec["ab_overrides"] = dict(stage_env)
                results.append(rec)
                stage_recs.append(rec)
            if any(r.get("skipped") for r in stage_recs):
                # bench.py skip artifacts (value 0.0, rc 0) are
                # NO-measurements: the stage must not mark done — the
                # armed watcher retries it (ADVICE r5 high)
                failed = True
            if name == "bench_prefix":
                winner_env = pick_winners(stage_recs)
            if name == "stage_bench":
                if persist_calibration(stage_recs, REPO):
                    print("== wrote BENCH_CALIBRATION.json ==",
                          file=sys.stderr, flush=True)
                ratio = pick_stream_ratio(stage_recs)
                if ratio is not None:
                    print("== stream routing: dense won (ratio %s) =="
                          % ratio, file=sys.stderr, flush=True)
        except Exception as e:      # keep later stages alive
            print("stage %s failed: %s" % (name, e), file=sys.stderr)
            results.append({"stage": name, "error": str(e)})
            failed = True
        write_out()
        if not failed:
            done.add(done_key)
            _save_done(done)
        else:
            any_failed = True
            if not tunnel_alive():
                print("== tunnel probe DEAD after %s: skipping remaining "
                      "stages ==" % name, file=sys.stderr, flush=True)
                dead = True

    # The canonical config-2 row = the measured winner of the routing
    # race, with the losing routing recorded alongside.  Read the race
    # rows back from the CANONICAL artifact (not just this attempt's
    # results): after a resume, one routing may have been measured in a
    # prior attempt, and crowning from a partial race would misreport
    # the winner.
    raced = {r["stage"]: r for r in results
             if r.get("stage", "").startswith("bench_configs:2:")
             and "value" in r and not r.get("skipped")}
    try:
        with open(CANON) as fh:
            for ln in fh:
                rec = json.loads(ln)
                if (rec.get("stage", "").startswith("bench_configs:2:")
                        and "value" in rec and not rec.get("skipped")
                        and rec["stage"] not in raced):
                    raced[rec["stage"]] = rec
    except (OSError, ValueError):
        pass
    def _resolved(tag: str) -> bool:
        # A routing is resolved once it has measured (any attempt) or
        # actually EXECUTED this attempt (a failed routing still lets
        # the surviving one be crowned; a later successful retry
        # re-crowns the full race and supersedes).  A "skipped: tunnel
        # dead" placeholder never ran — it must not resolve the race.
        full = "bench_configs:2:" + tag
        if any(k.split(":", 1)[1] == full for k in done):
            return True
        for r in results:
            if r.get("stage") != full:
                continue
            if r.get("skipped"):
                continue     # a skip artifact never measured: retry owed
            if "value" in r or not str(r.get("error", "")).startswith(
                    "skipped:"):
                return True
        return False
    if not (raced and all(_resolved(t) for t in ("dense", "segment"))):
        # A routing is still unresolved (pending retry): don't crown.
        raced = {}
    if raced:
        best = max(raced.values(), key=lambda r: r["value"])
        rest = [r for r in raced.values() if r is not best]
        row = dict(best)
        row["stage"] = "bench_configs:2"
        row["routing"] = best["stage"].rsplit(":", 1)[-1]
        if rest:
            row["losing_routing"] = {
                r["stage"].rsplit(":", 1)[-1]: r["value"] for r in rest}
        results.append(row)
        write_out()
    print("wrote %s (%d records)" % (OUT, len(results)))
    # Nonzero exit when stages remain unmeasured (tunnel died or a stage
    # failed) so the armed watcher retries; rc=0 marks the session done
    # and clears the resume state (a stale done file would make a future
    # re-armed session skip everything and report success on no work).
    if dead or any_failed:
        sys.exit(1)
    try:
        os.remove(DONE_STATE)
    except OSError:
        pass


if __name__ == "__main__":
    main()
