"""tsdbsan — the runtime sanitizer layer (the dynamic twin of tsdblint).

tools/lint/ proves lock discipline and kernel hygiene *statically*, but
its `# guarded-by:` annotations and lock-order graph are only as true as
the annotations.  tsdbsan is the complement that makes those contracts
trustworthy at test time:

  lockset   an instrumented lock wrapper substituted for
            threading.Lock/RLock inside opentsdb_tpu plus a
            write-interception layer on lock-holding classes.  Every
            annotated attribute mutation is verified to actually hold
            its declared lock (san-unguarded-mutation), and Eraser-style
            lockset intersection runs on *unannotated* attributes to
            surface shared state lint cannot see (san-lockset-race —
            the finding suggests the missing annotation).
  deadlock  records the runtime held-locks-at-acquire order graph,
            detects cycles/inversions (san-lock-order-inversion) and
            live wait-for cycles via a watchdog (san-deadlock), and
            cross-checks the observed graph against lock_discipline's
            static one (san-stale-static-edge / san-lint-gap notes).
  jax       counts trace/compile events per jitted kernel and
            device->host transfers; a hot kernel recompiling after
            warmup (san-recompile-after-warmup) or a host sync outside
            sanctioned sites (san-host-sync) during steady-state query
            serving is a finding.

Enable with `TSDBSAN=1` (the pytest plugin in tools/sanitize/plugin.py
arms automatically via tests/conftest.py), `tools/sanitize/run.py
--subset tier1` (one-shot CI entry), or `tsd.sanitizer.enable=true` on
a live daemon.  Findings flow through tools/lint's Finding/SARIF
machinery and honor the same `# tsdblint: disable=<rule>` suppressions.
"""

from __future__ import annotations

import os

ENABLE_ENV = "TSDBSAN"


def enabled() -> bool:
    """True when the ambient environment arms the sanitizer."""
    return os.environ.get(ENABLE_ENV, "") == "1"


from tools.sanitize.install import (  # noqa: E402
    install, installed, instrument_module, uninstall)
from tools.sanitize.report import REPORTER, SAN_RULES  # noqa: E402

__all__ = ["ENABLE_ENV", "enabled", "install", "installed",
           "instrument_module", "uninstall", "REPORTER", "SAN_RULES"]
