"""Deadlock watcher: runtime lock-order graph + live wait-for cycles.

Two complementary detectors over the instrumented locks:

  order graph   every acquire of a labeled lock while holding other
                labeled locks records `(held.label) -> (acquired.label)`
                edges in the same `(ClassName, lock_attr)` node space as
                lock_discipline's static graph.  `detect_inversions()`
                reports cycles (san-lock-order-inversion) — an inversion
                is a hazard even when the interleaving that would
                deadlock never happened this run.  Same-label edges
                (two instances of one class) only count as an inversion
                when BOTH instance orders were observed: acquiring
                peers in a consistent order is the sanctioned idiom.
  wait-for      a watchdog thread walks thread-waits-for-lock ->
                lock-owned-by-thread edges; a cycle is an ACTUAL
                deadlock in progress (san-deadlock).  The watchdog only
                exists while the sanitizer is installed with the
                deadlock detector enabled.

`cross_check()` diffs the observed order graph against the static one:
a static edge never observed is a stale-annotation/uncovered-path
report (san-stale-static-edge, note level); an observed edge the lint
cannot derive is a lint gap (san-lint-gap, note level).  Both are
deterministic given the same run: edges are sorted before reporting.

A third static<->dynamic bridge rides the same machinery: every
BLOCKED instrumented acquire is timed (SanLockBase.acquire ->
`record_blocked_wait`), and when the waiting thread carried a bounded
ambient request Deadline that expired DURING the wait, the site is
remembered.  `report_blocked_past_deadline()` emits those as
san-blocked-past-deadline notes, cross-referenced against
deadline_discipline's static request-path set
(tools/lint/blocking.static_request_paths) — the same pattern as the
order-graph's stale-edge/lint-gap notes — and tags sites the source
waived with `# blocking: bounded-by <reason>`.
"""

from __future__ import annotations

import os
import threading

from tools.sanitize.report import REPORTER, caller_site

Label = tuple[str, str]
Edge = tuple[Label, Label]

_RealLock = threading.Lock

_state_lock = _RealLock()
# (labelA -> labelB) -> (path, line) of the first acquire that created it
_order_edges: dict[Edge, tuple[str, int]] = {}
# same-label edges: label -> set of observed instance orders (+1 / -1)
_same_label_orders: dict[Label, dict[int, tuple[str, int]]] = {}
# thread ident -> SanLock it is blocked acquiring
_waiting: dict[int, object] = {}
# (path, line, func, lock name) -> longest blocked wait (seconds) that
# outlasted the ambient deadline's remainder at that site
_blocked_waits: dict[tuple[str, int, str, str], float] = {}

_watchdog: "_Watchdog | None" = None
_enabled = False


def configure(enabled: bool, watchdog_ms: int = 200) -> None:
    global _enabled, _watchdog
    _enabled = enabled
    if enabled and watchdog_ms > 0 and _watchdog is None:
        _watchdog = _Watchdog(watchdog_ms / 1000.0)
        _watchdog.start()
    elif not enabled and _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


def reset() -> None:
    with _state_lock:
        _order_edges.clear()
        _same_label_orders.clear()
        _waiting.clear()
        _blocked_waits.clear()


def snapshot_state() -> tuple:
    """Copy of the accumulated order-graph + blocked-wait state; fixture
    tests that seed deliberate inversions snapshot/restore around
    themselves so a TSDBSAN=1 session's real graph survives them."""
    with _state_lock:
        return (dict(_order_edges),
                {k: dict(v) for k, v in _same_label_orders.items()},
                dict(_blocked_waits))


def restore_state(snapshot: tuple) -> None:
    order, same, blocked = snapshot
    with _state_lock:
        _order_edges.clear()
        _order_edges.update(order)
        _same_label_orders.clear()
        for k, v in same.items():
            _same_label_orders[k] = dict(v)
        _blocked_waits.clear()
        _blocked_waits.update(blocked)


# --------------------------------------------------------------------- #
# Acquire-time recording (called from SanLockBase.acquire)              #
# --------------------------------------------------------------------- #

def record_acquire(lock, held) -> None:
    if not _enabled or lock.label is None:
        return
    site = None
    for h in held:
        if h is lock or h.label is None:
            continue
        if site is None:
            site = caller_site(skip=2)[:2]
        if h.label == lock.label:
            # two instances of the same (class, lock): record which
            # instance order this acquire exhibits
            order = 1 if id(h) < id(lock) else -1
            with _state_lock:
                _same_label_orders.setdefault(
                    lock.label, {}).setdefault(order, site)
        else:
            with _state_lock:
                _order_edges.setdefault((h.label, lock.label), site)


def report_self_deadlock(lock) -> None:
    """A non-reentrant Lock re-acquired by its owner: guaranteed
    self-deadlock.  Reported immediately — the thread is about to hang."""
    if not _enabled:
        return
    path, line, func = caller_site(skip=2)
    REPORTER.add(path, line, "san-deadlock",
                 "non-reentrant lock %s re-acquired by its owning thread "
                 "in '%s' — self-deadlock" % (lock.describe(), func))


def register_waiting(lock) -> None:
    if not _enabled:
        return
    with _state_lock:
        _waiting[threading.get_ident()] = lock


def unregister_waiting() -> None:
    if not _enabled:
        return
    with _state_lock:
        _waiting.pop(threading.get_ident(), None)


def record_blocked_wait(lock, waited_s: float) -> None:
    """Called from SanLockBase.acquire after a BLOCKED acquire path
    returns: when this thread carries a bounded ambient request
    Deadline that is expired NOW, the wait outlasted whatever remainder
    the deadline had when the wait began (remaining_before = remaining
    now + waited) — remember the site for the note-level
    blocked-past-deadline report."""
    if not _enabled or waited_s < 0.001:
        return
    try:
        from opentsdb_tpu.query.limits import active_deadline
    except ImportError:                  # sanitizer used standalone
        return
    dl = active_deadline()
    if dl is None or not dl.bounded or dl.remaining_ms() >= 0:
        return
    if lock.label is not None:
        name = "%s.%s" % lock.label
    else:
        name = "an unlabeled %s" % lock.kind
    path, line, func = caller_site(skip=2)
    key = (path, line, func, name)
    with _state_lock:
        if waited_s > _blocked_waits.get(key, 0.0):
            _blocked_waits[key] = waited_s


# --------------------------------------------------------------------- #
# Detection                                                             #
# --------------------------------------------------------------------- #

def observed_edges() -> dict[Edge, tuple[str, int]]:
    with _state_lock:
        out = dict(_order_edges)
        for label, orders in _same_label_orders.items():
            if len(orders) == 2:        # both instance orders seen
                out[(label, label)] = orders[1]
    return out


def detect_inversions() -> None:
    """Cycle-check the observed order graph and report each canonical
    cycle once.  Deterministic: nodes and successors visited sorted."""
    edges = observed_edges()
    graph: dict[Label, set[Label]] = {}
    for a, b in edges:
        if a == b:
            path, line = edges[(a, b)]
            REPORTER.add(
                path, line, "san-lock-order-inversion",
                "instances of %s.%s are acquired while holding another "
                "instance's %s in BOTH orders — lock-order inversion "
                "between peers (impose a canonical acquisition order)"
                % (a[0], a[1], a[1]))
            continue
        graph.setdefault(a, set()).add(b)
    seen_cycles: set[tuple] = set()
    for start in sorted(graph):
        stack = [(start, (start,))]
        while stack:
            node, path_nodes = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    body = path_nodes
                    k = min(range(len(body)),
                            key=lambda i: body[i:] + body[:i])
                    canon = body[k:] + body[:k]
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    fpath, fline = edges[(node, start)]
                    REPORTER.add(
                        fpath, fline, "san-lock-order-inversion",
                        "runtime lock-order cycle: " + " -> ".join(
                            "%s.%s" % n for n in canon + (canon[0],)))
                elif nxt not in path_nodes:
                    stack.append((nxt, path_nodes + (nxt,)))


class _Watchdog:
    """Periodically walks thread -> waits-for lock -> owning thread; a
    cycle means those threads are deadlocked RIGHT NOW."""

    def __init__(self, interval_s: float) -> None:
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        from tools.sanitize.locks import real_thread
        self._thread = real_thread(target=self._run, daemon=True,
                                   name="tsdbsan-deadlock-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.scan_once()

    @staticmethod
    def scan_once() -> None:
        with _state_lock:
            waits = dict(_waiting)
        # thread -> thread edges through lock ownership
        succ: dict[int, int] = {}
        for tid, lock in waits.items():
            owner = getattr(lock, "owner", None)
            if owner is not None and owner != tid:
                succ[tid] = owner
        reported: set[frozenset] = set()
        for start in sorted(succ):
            tid = start
            visited = [start]
            while tid in succ:
                tid = succ[tid]
                if tid == start:
                    cycle = frozenset(visited)
                    if cycle in reported:
                        break
                    reported.add(cycle)
                    locks = sorted(waits[t].describe() for t in cycle
                                   if t in waits)
                    first = waits.get(start)
                    path, line = "<runtime>", 0
                    if first is not None and first.label is not None:
                        path, line = _label_site(first.label)
                    REPORTER.add(
                        path, line, "san-deadlock",
                        "live deadlock: %d thread(s) in a wait-for "
                        "cycle over locks [%s]"
                        % (len(cycle), ", ".join(locks)))
                    break
                if tid in visited:
                    break       # cycle not through start; its own start
                visited.append(tid)


def _label_site(label: Label) -> tuple[str, int]:
    """Best-effort source anchor for a (Class, lock) label: the first
    recorded order-edge site touching it, else unknown."""
    with _state_lock:
        for (a, b), site in sorted(_order_edges.items()):
            if a == label or b == label:
                return site
        for lbl, orders in sorted(_same_label_orders.items()):
            if lbl == label:
                return sorted(orders.values())[0]
    return "<runtime>", 0


def scan_waiting_now() -> None:
    """One synchronous watchdog pass (tests drive this directly)."""
    _Watchdog.scan_once()


# --------------------------------------------------------------------- #
# Static <-> dynamic cross-check                                        #
# --------------------------------------------------------------------- #

def cross_check(static_edges: dict[Edge, tuple[str, int]] | None = None,
                observed: dict[Edge, tuple[str, int]] | None = None,
                reporter=None) -> dict[str, list[Edge]]:
    """Diff the runtime order graph against lock_discipline's static
    one.  Emits note-level findings (into `reporter`, default the
    process-global one) and returns the diff for callers that render it
    themselves."""
    if static_edges is None:
        static_edges = static_edges_with_sites()
    if observed is None:
        observed = observed_edges()
    rep = reporter if reporter is not None else REPORTER
    # same-label single-order observations are sanctioned (consistent
    # peer ordering) — only both-orders entries made it into observed.
    stale = sorted(set(static_edges) - set(observed))
    gaps = sorted(set(observed) - set(static_edges))
    for edge in stale:
        path, line = static_edges[edge]
        rep.add(
            path, line, "san-stale-static-edge",
            "static lock-order edge %s.%s -> %s.%s was never observed "
            "at runtime this session — stale annotation or uncovered "
            "path" % (edge[0] + edge[1]))
    for edge in gaps:
        path, line = observed[edge]
        rep.add(
            path, line, "san-lint-gap",
            "runtime lock-order edge %s.%s -> %s.%s is not derivable "
            "by lock_discipline — lint gap (annotate the attribute "
            "types so the static graph sees this call path)"
            % (edge[0] + edge[1]))
    return {"stale": stale, "gaps": gaps}


def blocked_waits() -> dict[tuple[str, int, str, str], float]:
    with _state_lock:
        return dict(_blocked_waits)


def report_blocked_past_deadline(reporter=None,
                                 static_paths: set[tuple[str, str]]
                                 | None = None,
                                 root: str | None = None) -> list:
    """Emit a san-blocked-past-deadline note for every recorded blocked
    acquire that outlasted its ambient deadline, cross-referenced
    against deadline_discipline's static request-path set (same
    static<->dynamic pattern as the stale-edge/lint-gap notes).  Sites
    the source waives with `# blocking: bounded-by <reason>` are tagged
    with the reason instead of a coverage verdict.  The static lint
    only runs when there is something to report (it costs a tree walk).
    Returns the emitted Finding keys, sorted."""
    events = blocked_waits()
    if not events:
        return []
    rep = reporter if reporter is not None else REPORTER
    if static_paths is None:
        static_paths = static_request_paths_cached(root)
    out = []
    for (path, line, func, lockname) in sorted(events):
        reason = _blocking_waiver(path, line, root)
        if reason is not None:
            tag = ("site waived in source: bounded-by %s — confirm the "
                   "waiver still holds under this deadline" % reason)
        elif (path, func) in static_paths:
            tag = ("on deadline_discipline's static request-path set — "
                   "the route is covered; tighten the acquire bound or "
                   "shed load before the critical section")
        else:
            tag = ("NOT in the static request-path set — uncovered "
                   "route or a non-request thread carrying a deadline "
                   "(possible lint gap)")
        rep.add(path, line, "san-blocked-past-deadline",
                "blocked acquire of %s in '%s' kept waiting past the "
                "ambient request deadline's remainder (%s)"
                % (lockname, func, tag))
        out.append((path, line, func, lockname))
    return out


_static_paths_cache: set[tuple[str, str]] | None = None


def static_request_paths_cached(root: str | None = None
                                ) -> set[tuple[str, str]]:
    """deadline_discipline's (path, function) request-path set, resolved
    lazily from the lint layer and cached for the process (the
    underlying pass walks the whole package)."""
    global _static_paths_cache
    if _static_paths_cache is None:
        from tools.lint.blocking import static_request_paths
        _static_paths_cache = static_request_paths(root)
    return _static_paths_cache


def _blocking_waiver(path: str, line: int,
                     root: str | None = None) -> str | None:
    """The `# blocking: bounded-by <reason>` waiver covering `line` of
    `path` (the site line or the line directly above — the same
    placement the lint grammar honors), or None."""
    from tools.lint.annotations import blocking_annotation
    from tools.lint.core import REPO_ROOT
    abspath = os.path.join(root or REPO_ROOT, path)
    try:
        with open(abspath, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return None
    for at in (line, line - 1):
        if 1 <= at <= len(lines):
            reason = blocking_annotation(lines[at - 1])
            if reason is not None:
                return reason
    return None


def save_observed(path: str) -> None:
    """Persist the observed graph (pytest sessions write this; run.py
    cross-checks it against the static graph afterwards)."""
    import json
    edges = observed_edges()
    payload = [{"from": list(a), "to": list(b),
                "path": site[0], "line": site[1]}
               for (a, b), site in sorted(edges.items())]
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_observed(path: str) -> dict[Edge, tuple[str, int]]:
    import json
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    return {(tuple(e["from"]), tuple(e["to"])): (e["path"], e["line"])
            for e in payload}


# static_order_edges returns a set of edges; the cross-check wants
# per-edge source anchors.  Resolve them lazily from lock_discipline.
def static_edges_with_sites(root: str | None = None
                            ) -> dict[Edge, tuple[str, int]]:
    from tools.lint.core import REPO_ROOT, LintContext, run_lint
    from tools.lint import lock_discipline
    ctx = LintContext(root or REPO_ROOT)
    run_lint(["opentsdb_tpu"], root=root or REPO_ROOT,
             analyzers=[lock_discipline.ANALYZER], ctx=ctx)
    classes = ctx.bucket("lock").get("classes", {})
    out: dict[Edge, tuple[str, int]] = {}
    for a, b, path, line in lock_discipline._cycle_edges(classes):
        out.setdefault((a, b), (path, line))
    return out
