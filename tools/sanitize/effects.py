"""Explain-sentinel: the dynamic half of effect_contract.

The static analyzer (tools/lint/effects.py) proves the `# effects:`
contracts over the call tree; this module watches REAL explain-tagged
requests.  While a thread is inside `explain_query` (the whole
/api/query/explain consult surface) the sentinel is ARMED:

  * the lockset write-interception layer (the `__setattr__` wrapper
    every guarded class already carries) forwards each attribute store
    here via `note_write` — a cheap dict insert, no tree walk;
  * the booby-trapped dispatch gateways (the exact set
    tests/test_explain.py pins flat) and `AdmissionGate.acquire` are
    wrapped as sentinels via the same PATCH_TABLE mechanism the order
    recorder uses.

Events are recorded deduplicated by (kind, detail) and cross-checked
against the static contract table at session finish
(`static_effect_table()` — contracts + the classes whose read-only
promise the lint verified).  The filter runs THERE, not on the write
path: a sanctioned store (a QueryBudget charge, a Series
canonicalization — `canonicalize` classes are deliberately absent from
the watched set) costs one dict lookup while armed and nothing at
finish, and a session that armed nothing returns without walking the
tree.

  san-effect-violation   an armed request wrote a watched class's
                         attribute, dispatched through a gateway, or
                         acquired an admission permit — an effect on
                         the read-only consult surface the static
                         verifier did not derive (monkey-patching,
                         reflection, or a call path outside the lint's
                         scope).  Note level: the static analyzer
                         gates; the runtime check reports.
"""

from __future__ import annotations

import threading

from tools.sanitize.report import REPORTER, caller_site

_RealLock = threading.Lock

_state_lock = _RealLock()
# (kind, detail) -> (path, line); kind in {"write", "dispatch", "permit"}
_events: dict[tuple[str, str], tuple[str, int]] = {}

_enabled = False
_static_table: dict | None = None

_armed = threading.local()

# module -> ((holder, attr-or-None, kind, detail), ...).  holder None =
# a module-level function; else (class name, method name).  These are
# the dispatch gateways the explain tests booby-trap, plus the
# admission permit — entering one while armed IS the finding.
PATCH_TABLE: dict[str, tuple] = {
    "opentsdb_tpu.ops.pipeline": tuple(
        (None, fn, "dispatch", "pipeline.%s" % fn)
        for fn in ("run_pipeline", "run_group_pipeline",
                   "run_union_batch_pipeline", "run_grid_tail",
                   "run_downsample_grid", "build_batch",
                   "build_batch_direct")),
    "opentsdb_tpu.ops.tiling": (
        (None, "run_tiled", "dispatch", "tiling.run_tiled"),),
    "opentsdb_tpu.storage.device_cache": (
        (None, "_gather_windows", "dispatch",
         "device_cache._gather_windows"),),
    "opentsdb_tpu.ops.streaming": (
        (("StreamAccumulator", "create"), None, "dispatch",
         "StreamAccumulator.create"),),
    "opentsdb_tpu.tsd.admission": (
        (("AdmissionGate", "acquire"), None, "permit",
         "AdmissionGate.acquire"),),
}

_ARM_MODULE = "opentsdb_tpu.query.explain"
_ARM_FUNCTION = "explain_query"

# (owner object, attr name, original) for unpatch_all()
_patched: list[tuple[object, str, object]] = []


def configure(enabled: bool) -> None:
    global _enabled
    _enabled = enabled


def reset() -> None:
    with _state_lock:
        _events.clear()


def snapshot_state() -> dict:
    with _state_lock:
        return dict(_events)


def restore_state(snapshot: dict) -> None:
    with _state_lock:
        _events.clear()
        _events.update(snapshot)


# --------------------------------------------------------------------- #
# Arming + recording                                                    #
# --------------------------------------------------------------------- #

def armed() -> bool:
    return _enabled and getattr(_armed, "depth", 0) > 0


def _record(kind: str, detail: str, skip: int = 0) -> None:
    key = (kind, detail)
    with _state_lock:
        known = key in _events
    if known:
        return
    path, line, _fn = caller_site(skip + 1)
    with _state_lock:
        _events.setdefault(key, (path, line))


def note_write(cls_name: str, attr: str) -> None:
    """Called by the lockset __setattr__ layer for every tracked store.
    The armed() guard is the caller's fast path; here we only dedup and
    anchor.  Filtering against the watched-class set happens at
    cross_check — this must stay O(1) per store."""
    _record("write", "%s.%s" % (cls_name, attr), skip=1)


def events() -> dict[tuple[str, str], tuple[str, int]]:
    with _state_lock:
        return dict(_events)


# --------------------------------------------------------------------- #
# Instrumentation                                                       #
# --------------------------------------------------------------------- #

def instrument_module(mod) -> int:
    """Wrap this module's sentinel entries (idempotent): the arming
    wrapper on `explain_query`, dispatch gateways, and the admission
    permit.  Returns the number of objects newly wrapped."""
    name = getattr(mod, "__name__", "")
    wrapped = 0
    if name == _ARM_MODULE:
        orig = mod.__dict__.get(_ARM_FUNCTION)
        if callable(orig) and not getattr(orig, "_tsdbsan_effects",
                                          False):
            setattr(mod, _ARM_FUNCTION, _arming_wrap(orig))
            _patched.append((mod, _ARM_FUNCTION, orig))
            wrapped += 1
    for holder, meth, kind, detail in PATCH_TABLE.get(name, ()):
        if holder is None:
            owner, attr = mod, meth
            orig = mod.__dict__.get(meth)
        else:
            cls_name, attr = holder
            owner = getattr(mod, cls_name, None)
            if not isinstance(owner, type):
                continue
            orig = owner.__dict__.get(attr)
            # classmethod/staticmethod wrappers: sentinel the inner
            # callable, re-wrap on the way back in
            if isinstance(orig, (classmethod, staticmethod)):
                inner = orig.__func__
                if getattr(inner, "_tsdbsan_effects", False):
                    continue
                probe = _sentinel_wrap(inner, kind, detail)
                setattr(owner, attr, type(orig)(probe))
                _patched.append((owner, attr, orig))
                wrapped += 1
                continue
        if not callable(orig) or getattr(orig, "_tsdbsan_effects",
                                         False):
            continue
        setattr(owner, attr, _sentinel_wrap(orig, kind, detail))
        _patched.append((owner, attr, orig))
        wrapped += 1
    return wrapped


def _arming_wrap(orig):
    def wrapper(*args, **kwargs):
        _armed.depth = getattr(_armed, "depth", 0) + 1
        try:
            return orig(*args, **kwargs)
        finally:
            _armed.depth -= 1
    wrapper._tsdbsan_effects = True
    wrapper.__name__ = getattr(orig, "__name__", _ARM_FUNCTION)
    wrapper.__doc__ = getattr(orig, "__doc__", None)
    return wrapper


def _sentinel_wrap(orig, kind: str, detail: str):
    def wrapper(*args, **kwargs):
        if armed():
            _record(kind, detail)
        return orig(*args, **kwargs)
    wrapper._tsdbsan_effects = True
    wrapper.__name__ = getattr(orig, "__name__", detail)
    wrapper.__doc__ = getattr(orig, "__doc__", None)
    return wrapper


def unpatch_all() -> None:
    while _patched:
        owner, attr, orig = _patched.pop()
        setattr(owner, attr, orig)


# --------------------------------------------------------------------- #
# Static <-> dynamic cross-check                                        #
# --------------------------------------------------------------------- #

def static_table_cached() -> dict:
    global _static_table
    if _static_table is None:
        from tools.lint.effects import static_effect_table
        _static_table = static_effect_table()
    return _static_table


def cross_check(static_table: dict | None = None,
                reporter=None) -> dict[str, list]:
    """Diff armed-request events against the static contract table.
    A session that armed nothing returns empty WITHOUT walking the
    tree."""
    local = events()
    if not local:
        return {"violations": []}
    if static_table is None:
        static_table = static_table_cached()
    rep = reporter if reporter is not None else REPORTER
    watched = set(static_table.get("watched_classes", ()))
    violations: list[tuple[str, str]] = []
    for (kind, detail), (path, line) in sorted(local.items()):
        if kind == "write":
            cls_name = detail.split(".", 1)[0]
            if cls_name not in watched:
                continue    # sanctioned store (budget charge,
                #             canonicalization, non-contract class)
            rep.add(path, line, "san-effect-violation",
                    "an explain-tagged request wrote '%s' at runtime — "
                    "the consult surface's read-only contract "
                    "(verified statically by effect_contract) was "
                    "violated on a real execution" % detail)
        elif kind == "dispatch":
            rep.add(path, line, "san-effect-violation",
                    "an explain-tagged request entered dispatch "
                    "gateway '%s' at runtime — the explain route must "
                    "never hand the backend work (dispatch_purity "
                    "verifies this statically)" % detail)
        else:
            rep.add(path, line, "san-effect-violation",
                    "an explain-tagged request acquired admission "
                    "permit '%s' at runtime — explain must never "
                    "consume serving capacity" % detail)
        violations.append((kind, detail))
    return {"violations": violations}
