"""tsdbsan installation: patching orchestration + import hook.

`install()` arms the detectors process-wide:

  1. threading.Lock/RLock factories are swapped (tools/sanitize/locks)
     so lock constructions INSIDE the sanitized packages yield
     instrumented wrappers;
  2. every already-loaded `opentsdb_tpu.*` module is scanned with the
     shared annotation parser and its lock-holding classes get the
     write-interception layer (tools/sanitize/lockset);
  3. a meta-path hook instruments modules imported LATER the same way —
     lazy imports (the parallel/ mesh path, plugins) are covered
     without importing anything eagerly (importing parallel/ on a
     machine without shard_map must not become the sanitizer's fault);
  4. the deadlock watchdog starts (tools/sanitize/deadlock) and the
     runtime ordering recorder arms (tools/sanitize/order) — the same
     module scan wraps the patch-table methods that realise tagged
     order events — as does the explain effect sentinel
     (tools/sanitize/effects): dispatch gateways, the admission
     permit, and the `explain_query` arming wrapper;
  5. optionally the JAX compile/sync sanitizer attaches
     (tools/sanitize/jax_san) — off by default under pytest, where
     compiles happen throughout; the steady-state serving check and
     the daemon mode turn it on.

`uninstall()` restores everything it patched.  Already-constructed
locks stay wrapped (they are real locks underneath and behave
identically); already-instrumented classes are restored.
"""

from __future__ import annotations

import importlib.machinery
import os
import sys

from tools.lint.core import REPO_ROOT

DEFAULT_PACKAGES = ("opentsdb_tpu",)

_installed: dict | None = None


def installed() -> bool:
    return _installed is not None


def install(lockset: bool = True, deadlock_watch: bool = True,
            jax: bool = False, watchdog_ms: int = 200,
            packages: tuple[str, ...] = DEFAULT_PACKAGES,
            extra_lock_prefixes: tuple[str, ...] = ()) -> None:
    """Idempotent; a second install() is a no-op."""
    global _installed
    if _installed is not None:
        return
    from tools.sanitize import deadlock, effects, jax_san, locks
    from tools.sanitize import lockset as ls
    from tools.sanitize import order
    lock_prefixes = tuple(packages) + tuple(extra_lock_prefixes)
    locks.patch_factories(lock_prefixes)
    ls.configure(lockset_enabled=lockset)
    deadlock.configure(enabled=deadlock_watch, watchdog_ms=watchdog_ms)
    order.configure(enabled=True)
    effects.configure(enabled=True)
    instrumented: list[type] = []
    for modname in sorted(sys.modules):
        if _in_packages(modname, packages):
            instrumented.extend(instrument_module(sys.modules[modname]))
    hook = _SanImportHook(packages)
    sys.meta_path.insert(0, hook)
    jsan = None
    if jax:
        jsan = jax_san.JaxSanitizer()
        jsan.start()
    _installed = {
        "hook": hook,
        "classes": instrumented,
        "jax": jsan,
        "packages": packages,
    }


def uninstall() -> None:
    global _installed
    if _installed is None:
        return
    from tools.sanitize import deadlock, effects, locks
    from tools.sanitize import lockset as ls
    from tools.sanitize import order
    state, _installed = _installed, None
    try:
        sys.meta_path.remove(state["hook"])
    except ValueError:
        pass
    for cls in state["classes"]:
        ls.uninstrument_class(cls)
    if state["jax"] is not None:
        state["jax"].stop()
    deadlock.configure(enabled=False)
    order.configure(enabled=False)
    order.unpatch_all()
    effects.configure(enabled=False)
    effects.unpatch_all()
    locks.unpatch_factories()


def jax_sanitizer():
    """The active JaxSanitizer, or None when jax accounting is off."""
    return _installed["jax"] if _installed else None


def reset_state() -> None:
    """Drop accumulated detector state (not the patches): fixture tests
    isolate scenarios with this."""
    from tools.sanitize import deadlock, effects, lockset as ls
    from tools.sanitize import order
    from tools.sanitize.report import REPORTER
    deadlock.reset()
    ls.reset()
    order.reset()
    effects.reset()
    REPORTER.clear()
    if _installed and _installed["jax"] is not None:
        _installed["jax"].reset()


def _in_packages(modname: str, packages: tuple[str, ...]) -> bool:
    return any(modname == p or modname.startswith(p + ".")
               for p in packages)


def instrument_module(mod) -> list[type]:
    """Scan one loaded module's SOURCE with the shared annotation
    parser and instrument its lock-holding classes.  Public so fixture
    tests can instrument tests/san_fixtures modules explicitly."""
    from tools.lint.annotations import scan_module_file
    from tools.sanitize import effects
    from tools.sanitize import lockset as ls
    from tools.sanitize import order
    order.instrument_module(mod)
    effects.instrument_module(mod)
    path = getattr(mod, "__file__", None)
    if not path or not path.endswith(".py") or not os.path.exists(path):
        return []
    try:
        rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
        anns = scan_module_file(path, rel)
    except (OSError, SyntaxError, UnicodeDecodeError):
        return []
    out: list[type] = []
    for name, ann in sorted(anns.items()):
        if not ann.locks:
            continue
        cls = getattr(mod, name, None)
        if not isinstance(cls, type) or \
                getattr(cls, "__module__", None) != mod.__name__:
            continue
        if ls.instrument_class(cls, ann):
            out.append(cls)
    return out


class _SanImportHook:
    """Meta-path finder that lets the normal machinery find the module,
    then instruments it right after execution."""

    def __init__(self, packages: tuple[str, ...]) -> None:
        self._packages = packages

    def find_spec(self, fullname, path=None, target=None):
        if not _in_packages(fullname, self._packages):
            return None
        try:
            spec = importlib.machinery.PathFinder.find_spec(fullname, path)
        except (ImportError, ValueError):
            return None
        if spec is None or spec.loader is None:
            return None
        spec.loader = _WrappingLoader(spec.loader)
        return spec


class _WrappingLoader:
    def __init__(self, inner) -> None:
        self._inner = inner

    def create_module(self, spec):
        create = getattr(self._inner, "create_module", None)
        return create(spec) if create else None

    def exec_module(self, module) -> None:
        self._inner.exec_module(module)
        try:
            state = _installed
            if state is not None:
                state["classes"].extend(instrument_module(module))
        except Exception:       # noqa: BLE001 — never break an import
            pass

    def __getattr__(self, name):
        return getattr(self._inner, name)
