"""JAX compile/sync sanitizer: recompiles after warmup, host syncs.

High-throughput aggregation engines gate performance on ZERO hidden
recompiles and zero accidental device->host round-trips on the hot
query path.  tsdblint's jax_hygiene analyzer proves the *shape* of the
code (no per-call jit construction, no `.item()` on traced values);
this module proves the *behavior*:

  compile accounting   subscribes to the SHARED compile-log capture
        (opentsdb_tpu/obs/jaxprof.py CompileLogCapture — the same
        event stream tsdbobs's per-kernel compile counters consume, so
        the profiler and the sanitizer cannot drift).  The capture owns
        `jax_log_compiles` and the pxla "Compiling <kernel> ..."
        logging handler.  The run has two phases: warmup (compiles are
        expected and counted) and steady (entered via `mark_steady()`).
        Any compile event in steady state is a finding
        (san-recompile-after-warmup) attributed to the repo call site
        that triggered it — subscribers run synchronously in the
        compiling thread, so the stack still shows who asked.
  host-sync accounting  ArrayImpl's device->host surfaces (`__array__`,
        `item`, `tolist`, `__float__`, `__int__`, `__bool__`,
        `__index__`) are wrapped.  In steady state a transfer outside a
        sanctioned site is a finding (san-host-sync).  Sanctioned =
        inside a `sanctioned()` context, or any stack frame matching
        the SANCTIONED_SITES registry (the serialization boundary is
        where results legitimately leave the device).
  cache-size pinning    `snapshot_kernel_caches()` records
        `_cache_size()` of every module-scope jitted kernel in ops/ +
        parallel/; `check_cache_growth(snapshot)` reports kernels whose
        cache grew — per-kernel attribution that survives even when log
        capture is off.

Everything installs lazily and restores on stop(); with the sanitizer
off this module costs nothing.
"""

from __future__ import annotations

import sys
import threading

from opentsdb_tpu.obs.jaxprof import compile_capture
from tools.sanitize.report import REPORTER, caller_site

# (path suffix, function-name prefix) pairs whose presence anywhere on
# the stack sanctions a host sync: the serialization boundary and the
# planner's explicit result materialization are where query results are
# SUPPOSED to leave the device.  Keep this list short and justified —
# every entry is a hole in the detector.
SANCTIONED_SITES: list[tuple[str, str]] = [
    ("opentsdb_tpu/tsd/serializers.py", ""),
    ("opentsdb_tpu/query/planner.py", "_materialize"),
    ("opentsdb_tpu/ops/hostlane.py", ""),
    # the tracer's device_wait: per-stage device timing is a DELIBERATE
    # stage-boundary rendezvous (tsd.trace.device_time) — the one sync
    # the trace path is allowed
    ("opentsdb_tpu/obs/trace.py", ""),
]

_tls = threading.local()


class sanctioned:
    """`with jax_san.sanctioned():` — host syncs inside are expected."""

    def __enter__(self):
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.depth = getattr(_tls, "depth", 1) - 1


def _in_sanctioned_context() -> bool:
    return getattr(_tls, "depth", 0) > 0


def _at_sanctioned_site() -> bool:
    f = sys._getframe(2)
    hops = 0
    while f is not None and hops < 40:
        fn = f.f_code.co_filename.replace("\\", "/")
        for suffix, func_prefix in SANCTIONED_SITES:
            if fn.endswith(suffix) and \
                    f.f_code.co_name.startswith(func_prefix):
                return True
        f = f.f_back
        hops += 1
    return False


class JaxSanitizer:
    """One installable instance (tools/sanitize/install.py owns it)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()   # captured pre-patch via import time
        self.phase = "warmup"
        self.compiles: dict[str, dict[str, int]] = {}
        self.host_syncs: dict[str, int] = {}
        self._subscribed = False
        self._array_patches: list[tuple[type, str, object]] = []

    # -- lifecycle --

    def start(self) -> None:
        self.phase = "warmup"
        if not self._subscribed:
            # the shared capture (obs/jaxprof.py) owns jax_log_compiles
            # and the pxla handler; this instance just subscribes
            compile_capture.subscribe(self._on_compile)
            self._subscribed = True
        self._patch_array_type()

    def stop(self) -> None:
        if self._subscribed:
            compile_capture.unsubscribe(self._on_compile)
            self._subscribed = False
        for cls, name, orig in self._array_patches:
            setattr(cls, name, orig)
        self._array_patches = []

    def reset(self) -> None:
        with self._lock:
            self.phase = "warmup"
            self.compiles.clear()
            self.host_syncs.clear()

    def mark_steady(self) -> None:
        self.phase = "steady"

    # -- compile accounting --

    def _on_compile(self, kernel: str) -> None:
        with self._lock:
            per = self.compiles.setdefault(kernel,
                                           {"warmup": 0, "steady": 0})
            per[self.phase] += 1
            steady = self.phase == "steady"
        if steady:
            path, line, func = caller_site(skip=2)
            REPORTER.add(
                path, line, "san-recompile-after-warmup",
                "kernel '%s' compiled during steady state (triggered "
                "from '%s') — a hot serving path is recompiling after "
                "warmup" % (kernel, func))

    # -- host-sync accounting --

    def _patch_array_type(self) -> None:
        import jax.numpy as jnp
        cls = type(jnp.asarray(0))
        for name in ("__array__", "item", "tolist", "__float__",
                     "__int__", "__bool__", "__index__"):
            orig = getattr(cls, name, None)
            if orig is None:
                continue
            wrapper = self._make_sync_wrapper(name, orig)
            try:
                setattr(cls, name, wrapper)
            except (AttributeError, TypeError):
                continue
            self._array_patches.append((cls, name, orig))

    def _make_sync_wrapper(self, name: str, orig):
        san = self

        def _wrapped(array_self, *args, **kwargs):
            san._on_host_sync(name)
            return orig(array_self, *args, **kwargs)

        _wrapped.__name__ = name
        return _wrapped

    def _on_host_sync(self, surface: str) -> None:
        if self.phase != "steady":
            return
        if _in_sanctioned_context() or _at_sanctioned_site():
            return
        path, line, func = caller_site(skip=2)
        with self._lock:
            self.host_syncs[path] = self.host_syncs.get(path, 0) + 1
        REPORTER.add(
            path, line, "san-host-sync",
            "device->host transfer (%s) in '%s' during steady state, "
            "outside every sanctioned site — a hidden sync on the hot "
            "path" % (surface, func))


# --------------------------------------------------------------------- #
# Module-scope jitted kernel cache pinning                              #
# --------------------------------------------------------------------- #

KERNEL_MODULE_PREFIXES = ("opentsdb_tpu.ops.", "opentsdb_tpu.parallel.")


def snapshot_kernel_caches() -> dict[str, int]:
    """{qualified kernel name: jit cache size} for every module-scope
    jitted binding in the loaded ops/ + parallel/ modules."""
    out: dict[str, int] = {}
    for modname, mod in sorted(sys.modules.items()):
        if mod is None or not modname.startswith(KERNEL_MODULE_PREFIXES):
            continue
        for attr, value in sorted(vars(mod).items()):
            size_fn = getattr(value, "_cache_size", None)
            if callable(size_fn):
                try:
                    out["%s.%s" % (modname, attr)] = int(size_fn())
                except Exception:       # noqa: BLE001
                    continue
    return out


def check_cache_growth(before: dict[str, int]) -> list[str]:
    """Kernels whose jit cache grew since `before`; each one reports
    san-recompile-after-warmup with per-kernel attribution."""
    grown = []
    after = snapshot_kernel_caches()
    for kernel in sorted(before):
        if after.get(kernel, 0) > before[kernel]:
            grown.append(kernel)
            modname = kernel.rsplit(".", 1)[0]
            mod = sys.modules.get(modname)
            path = getattr(mod, "__file__", "<unknown>") or "<unknown>"
            from tools.sanitize.report import rel_path
            REPORTER.add(
                rel_path(path), 0, "san-recompile-after-warmup",
                "jitted kernel %s cache grew %d -> %d across the steady "
                "phase — a new shape/dtype reached a warm kernel"
                % (kernel, before[kernel], after.get(kernel, 0)))
    return grown
