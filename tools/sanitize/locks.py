"""Instrumented lock wrappers — the substrate every tsdbsan detector
shares.

`install()` swaps `threading.Lock` / `threading.RLock` for factories
that hand instrumented wrappers to callers inside the sanitized
packages (decided by the constructing frame's module, so stdlib and
third-party locks stay untouched and late imports are covered without
an import hook for lock creation itself).

Each SanLock knows its owner thread, recursion count, and — once the
write-interception layer sees it assigned to `self.<attr>` of a
lock-holding class — its `(ClassName, attr)` label, the node identity
shared with lock_discipline's static order graph.  A thread-local stack
of currently-held wrappers feeds the lockset race detector (which locks
protect this write?) and the deadlock watcher (which edges does this
acquire create, and who waits for whom?).
"""

from __future__ import annotations

import threading
import time

# the real factories, captured at import time (install() patches the
# module attributes; everything in here must keep using the real ones)
_RealLock = threading.Lock
_RealRLock = threading.RLock
real_thread = threading.Thread
get_ident = threading.get_ident

_tls = threading.local()


def held_locks() -> tuple["SanLockBase", ...]:
    """The instrumented locks the calling thread currently holds,
    outermost first (reentrant holds appear once per acquire)."""
    return tuple(getattr(_tls, "held", ()))


class SanLockBase:
    """Wrapper over a real lock: context manager + acquire/release with
    ownership tracking.  `label` is None until the write-interception
    layer observes the assignment `self.<attr> = <this lock>` on an
    instrumented class."""

    kind = "Lock"
    __slots__ = ("_inner", "label", "owner", "count")

    def __init__(self) -> None:
        self._inner = self._make_inner()
        self.label: tuple[str, str] | None = None
        self.owner: int | None = None
        self.count = 0

    def _make_inner(self):
        return _RealLock()

    # -- introspection used by the detectors --

    def held_by_me(self) -> bool:
        return self.owner == get_ident() and self.count > 0

    def describe(self) -> str:
        if self.label is not None:
            return "%s.%s" % self.label
        return "<unlabeled %s at 0x%x>" % (self.kind, id(self))

    # -- the lock protocol --

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        from tools.sanitize import deadlock
        me = get_ident()
        reentrant = self.kind == "RLock" and self.owner == me
        if not reentrant:
            deadlock.record_acquire(self, held_locks())
            if self.kind == "Lock" and self.owner == me and blocking:
                deadlock.report_self_deadlock(self)
        got = self._inner.acquire(False)
        if not got and blocking:
            if not reentrant:
                deadlock.register_waiting(self)
            waited_from = time.monotonic()
            try:
                got = self._inner.acquire(True, timeout)
            finally:
                if not reentrant:
                    deadlock.unregister_waiting()
                # the blocked-past-deadline watcher wants the time this
                # thread spent parked, timeout or not — a failed timed
                # acquire still stalled the request for its full timeout
                deadlock.record_blocked_wait(
                    self, time.monotonic() - waited_from)
        if got:
            if self.owner == me:
                self.count += 1
            else:
                self.owner = me
                self.count = 1
            held = getattr(_tls, "held", None)
            if held is None:
                held = []
                _tls.held = held
            held.append(self)
        return got

    def release(self) -> None:
        # bookkeeping FIRST: the instant the real lock frees, a blocked
        # acquire() may set owner/count for the new holder — updating
        # after self._inner.release() would clobber the waiter's state
        # and seed false unguarded-mutation/lockset findings on
        # correctly-locked code under contention
        prev_owner, prev_count = self.owner, self.count
        self.count -= 1
        if self.count <= 0:
            self.owner = None
            self.count = 0
        held = getattr(_tls, "held", None)
        removed = False
        if held is not None:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    removed = True
                    break
        try:
            self._inner.release()   # raises on foreign release, like real
        except BaseException:
            self.owner, self.count = prev_owner, prev_count
            if removed:
                held.append(self)
            raise

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return "<San%s %s owner=%s count=%d>" % (
            self.kind, self.describe(), self.owner, self.count)


class SanLock(SanLockBase):
    kind = "Lock"
    __slots__ = ()


class SanRLock(SanLockBase):
    kind = "RLock"
    __slots__ = ()

    def _make_inner(self):
        return _RealRLock()

    def _is_owned(self) -> bool:        # Condition(RLock) compatibility
        return self.held_by_me()


_san_prefixes: tuple[str, ...] = ()


def _caller_wants_san() -> bool:
    import sys
    mod = sys._getframe(2).f_globals.get("__name__", "")
    return mod.startswith(_san_prefixes)


def _factory_lock():
    if _san_prefixes and _caller_wants_san():
        return SanLock()
    return _RealLock()


def _factory_rlock():
    if _san_prefixes and _caller_wants_san():
        return SanRLock()
    return _RealRLock()


def patch_factories(prefixes: tuple[str, ...]) -> None:
    """Constructions of threading.Lock()/RLock() from modules whose
    dotted name starts with one of `prefixes` now yield instrumented
    wrappers; everything else keeps getting real locks."""
    global _san_prefixes
    _san_prefixes = tuple(prefixes)
    threading.Lock = _factory_lock
    threading.RLock = _factory_rlock


def unpatch_factories() -> None:
    global _san_prefixes
    _san_prefixes = ()
    threading.Lock = _RealLock
    threading.RLock = _RealRLock
