"""Lockset race detector: write interception on lock-holding classes.

`instrument_class()` swaps `__setattr__` on classes the shared
annotation parser (tools/lint/annotations.py) identifies as lock-holding.
Every attribute write is then tracked:

  * assignment of an instrumented lock to a declared lock attribute
    labels the lock `(ClassName, attr)` — the node identity the
    deadlock watcher and the static cross-check share — and registers
    it in the instance's lock table;
  * a write to a `# guarded-by:`-annotated attribute verifies the
    declared lock is actually held by the writing thread
    (san-unguarded-mutation).  Exemptions: `__init__` writing its own
    `self` (mirroring lint), plus a dynamic one the linter cannot
    have — writes while the instance has only ever been touched by a
    single thread (pre-publication construction, factory fill-in).
    Unlike lint, `*_locked` methods are NOT exempt: the caller-holds-
    the-lock convention is exactly what the runtime can check, so a
    `*_locked` method reached without the lock reports;
  * writes to *unannotated* attributes run Eraser-style lockset
    intersection (san-lockset-race).  State machine per (instance,
    attr): VIRGIN -> EXCLUSIVE(first thread; no checking) -> SHARED on
    the first foreign write (candidate lockset := locks held then).
    Each further write intersects the lockset with the locks held; a
    finding fires only when the lockset is empty AND at least two
    distinct threads wrote in the SHARED state — so the benign
    construct-then-hand-off pattern stays silent, while true
    multi-writer sharing with no common lock reports and suggests the
    missing `# guarded-by:` annotation.

Tracking runs AFTER the real write and never raises into application
code: a sanitizer bug degrades to a missed finding, not a crashed TSD.
"""

from __future__ import annotations

import sys
import threading
import weakref

from tools.lint.annotations import ClassAnnotations
from tools.sanitize import effects
from tools.sanitize.locks import SanLockBase, held_locks
from tools.sanitize.report import REPORTER, rel_path

_RealLock = threading.Lock
get_ident = threading.get_ident

_EXCLUSIVE = 0
_SHARED = 1


class _AttrState:
    __slots__ = ("state", "owner", "lockset", "writers", "reported")

    def __init__(self, owner: int) -> None:
        self.state = _EXCLUSIVE
        self.owner = owner
        self.lockset: frozenset | None = None
        self.writers: set[int] | None = None
        self.reported = False


class _InstState:
    __slots__ = ("locks", "attrs", "threads")

    def __init__(self) -> None:
        self.locks: dict[str, SanLockBase] = {}   # lock attr -> wrapper
        self.attrs: dict[str, _AttrState] = {}
        self.threads: set[int] = set()


_states_lock = _RealLock()
_weak_states: "weakref.WeakKeyDictionary[object, _InstState]" = \
    weakref.WeakKeyDictionary()
_id_states: dict[int, _InstState] = {}     # fallback for non-weakrefables
_lockset_enabled = True


def configure(lockset_enabled: bool) -> None:
    global _lockset_enabled
    _lockset_enabled = lockset_enabled


def reset() -> None:
    with _states_lock:
        _weak_states.clear()
        _id_states.clear()


def _state_for(obj) -> _InstState:
    with _states_lock:
        try:
            st = _weak_states.get(obj)
            if st is None:
                st = _InstState()
                _weak_states[obj] = st
            return st
        except TypeError:
            st = _id_states.get(id(obj))
            if st is None:
                st = _InstState()
                _id_states[id(obj)] = st
            return st


def instance_lock(obj, lock_attr: str) -> SanLockBase | None:
    """The instrumented lock registered under `lock_attr` for `obj`
    (None when the instance was built before install)."""
    return _state_for(obj).locks.get(lock_attr)


_MARK = "_tsdbsan_instrumented"


def instrument_class(cls: type, ann: ClassAnnotations) -> bool:
    """Wrap cls.__setattr__ (tracking) and cls.__init__ (stale-state
    purge for the id-keyed fallback).  Returns False when the class was
    already instrumented or defines a custom __setattr__ (out of scope
    — none in this tree)."""
    if _MARK in cls.__dict__:
        return False
    for klass in cls.__mro__:
        if klass is object:
            break
        fn = klass.__dict__.get("__setattr__")
        if fn is not None and not getattr(fn, "_tsdbsan_wrapper", False):
            return False        # custom __setattr__: leave it alone

    def _san_setattr(self, name, value, _ann=ann):
        object.__setattr__(self, name, value)
        try:
            _track(self, _ann, name, value)
        except Exception:       # noqa: BLE001 — never break the app
            pass

    # __slots__ classes without __weakref__ (Series — the densest
    # instrumented type) fall back to id-keyed state; CPython reuses a
    # freed instance's address, so a new object could inherit a dead
    # one's Eraser state and report false races.  Purging at __init__
    # makes every construction start VIRGIN.
    had_own_init = "__init__" in cls.__dict__
    orig_init = cls.__init__

    def _san_init(self, *args, _orig=orig_init, **kwargs):
        with _states_lock:
            _id_states.pop(id(self), None)
        return _orig(self, *args, **kwargs)

    _san_init._tsdbsan_wrapper = True
    _san_init._tsdbsan_orig = orig_init
    _san_init._tsdbsan_had_own = had_own_init
    cls.__setattr__ = _san_setattr
    cls.__init__ = _san_init
    setattr(cls, _MARK, True)
    return True


def uninstrument_class(cls: type) -> None:
    if _MARK in cls.__dict__:
        try:
            del cls.__setattr__
        except AttributeError:
            pass
        init = cls.__dict__.get("__init__")
        if init is not None and getattr(init, "_tsdbsan_wrapper", False):
            if init._tsdbsan_had_own:
                cls.__init__ = init._tsdbsan_orig
            else:
                try:
                    del cls.__init__
                except AttributeError:
                    pass
        delattr(cls, _MARK)


def _track(obj, ann: ClassAnnotations, name: str, value) -> None:
    if name.startswith("__") or name.startswith("_tsdbsan"):
        return
    if name in ann.locks:
        if isinstance(value, SanLockBase):
            if value.label is None:
                value.label = (ann.name, name)
            _state_for(obj).locks[name] = value
        return
    if isinstance(value, SanLockBase):
        return                   # a lock stored under a non-lock name
    if effects.armed():
        # explain-sentinel: record the store while an explain-tagged
        # request is live; the read-only cross-check filters at finish
        effects.note_write(ann.name, name)
    st = _state_for(obj)
    me = get_ident()
    st.threads.add(me)
    guarded = ann.guarded.get(name)
    if guarded is not None:
        _check_guarded(obj, ann, st, name, guarded, me)
    elif _lockset_enabled:
        _eraser(ann, st, name, me)


def _check_guarded(obj, ann: ClassAnnotations, st: _InstState, name: str,
                   lock_attr: str, me: int) -> None:
    lock = st.locks.get(lock_attr)
    if lock is not None and lock.owner == me and lock.count > 0:
        return                   # declared lock held: the contract holds
    if len(st.threads) < 2:
        return                   # pre-publication: single-thread so far
    if lock is None:
        return                   # lock predates install; cannot judge
    # mirror the static exemptions: the writer frame being this object's
    # __init__ or a *_locked method (caller-holds-the-lock convention is
    # still checked — the lock above was NOT held, so _locked methods do
    # report; only __init__ re-entry stays exempt)
    f = sys._getframe(3)         # _check_guarded <- _track <- setattr <- writer
    if f.f_code.co_name == "__init__" and f.f_locals.get("self") is obj:
        return
    REPORTER.add(
        rel_path(f.f_code.co_filename), f.f_lineno,
        "san-unguarded-mutation",
        "%s.%s (guarded-by %s) was mutated in '%s' without the lock "
        "held" % (ann.name, name, lock_attr, f.f_code.co_name))


def _eraser(ann: ClassAnnotations, st: _InstState, name: str,
            me: int) -> None:
    astate = st.attrs.get(name)
    if astate is None:
        st.attrs[name] = _AttrState(me)
        return
    if astate.state == _EXCLUSIVE:
        if astate.owner == me:
            return
        astate.state = _SHARED
        astate.lockset = frozenset(
            lk for lk in held_locks() if lk.count > 0)
        astate.writers = {me}
        return
    held = frozenset(lk for lk in held_locks() if lk.count > 0)
    astate.lockset = (astate.lockset or frozenset()) & held
    astate.writers.add(me)
    if astate.reported or astate.lockset or len(astate.writers) < 2:
        return
    astate.reported = True
    f = sys._getframe(3)         # _eraser <- _track <- setattr <- writer
    locks_held_names = sorted(lk.describe() for lk in held) or ["none"]
    class_locks = ", ".join(sorted(ann.locks)) or "none"
    REPORTER.add(
        rel_path(f.f_code.co_filename), f.f_lineno, "san-lockset-race",
        "%s.%s is written by multiple threads with no common lock — "
        "likely missing '# guarded-by:' annotation (class locks: %s; "
        "locks at last write: %s)"
        % (ann.name, name, class_locks, ", ".join(locks_held_names)))
