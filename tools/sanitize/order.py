"""Runtime ordering recorder: the dynamic half of order_contract.

The static analyzer (tools/lint/ordering.py) verifies declared
happens-before contracts (`# order: <a> before <b>`) against the call
tree; this module verifies them against EXECUTIONS.  A small patch
table wraps the product methods that realise tagged order events —
`Series.append` is the memstore-write, `DiskPersistence.journal` is
the wal-append, and so on — and every wrapped call appends the event
to a per-stream log.  A stream is one request trace when the ambient
obs.trace is active (`trace:<id>`), else the recording thread
(`thread:<ident>`): ordering contracts are per-request properties, so
events from different requests must never be compared against each
other.

Only the FIRST occurrence of each event per stream is retained — the
cross-check compares first-occurrence ranks, so a million appends cost
one dict entry, not a million tuples.

`cross_check()` diffs the streams against the lint's static contract
table (tools.lint.ordering.static_order_table, resolved lazily and
cached so a session pays for one tree walk at most):

  san-order-violation   a stream emitted b before a for a declared
                        contract `a before b` — the static verifier
                        missed an interleaving that really happened
                        (or an unannotated call path sequences the
                        pair).  Note level: the static analyzer gates;
                        the runtime check reports.
  san-order-gap         an instrumented, contracted event was never
                        observed all session — uncovered path or a
                        probe left behind after the tagged site moved.
                        Events with no probe (catch-up-pull,
                        rejoin-ready, epoch-bump, jit-cache-splice,
                        wal-close, spill-close, flightrec-shutdown,
                        permit-release) are exempt: they fire on
                        rejoin/shutdown paths a normal session never
                        takes, and an always-on gap report is noise.

Both are deterministic given the same run: streams and contracts are
sorted before reporting, and messages carry no stream ids (fingerprint
dedup collapses the same inversion across ten thousand requests into
one finding).
"""

from __future__ import annotations

import threading

from tools.sanitize.report import REPORTER, caller_site

# captured before tools/sanitize/locks.py patches the factories
_RealLock = threading.Lock

_state_lock = _RealLock()
# stream key -> {event -> (rank, path, line)}; rank is the stream's
# event counter at first occurrence
_streams: dict[str, dict[str, tuple[int, str, int]]] = {}
# stream key -> events recorded so far (including repeats)
_counts: dict[str, int] = {}

_enabled = False
_static_table: dict | None = None

# module -> ((class, method, event, when), ...); `when` is "after" for
# the write side (the event happened only if the call returned) and
# "before" for the publish side (recording the ack/mark at entry keeps
# its rank earliest — conservative for b-before-a detection).
PATCH_TABLE: dict[str, tuple[tuple[str, str, str, str], ...]] = {
    "opentsdb_tpu.storage.memstore": (
        ("Series", "append", "memstore-write", "after"),
        ("Series", "append_batch", "memstore-write", "after"),
        ("MemStore", "notify_mutation", "memstore-mark", "before"),
    ),
    "opentsdb_tpu.storage.persist": (
        ("DiskPersistence", "journal", "wal-append", "after"),
    ),
    "opentsdb_tpu.tsd.replication": (
        ("ReplicationManager", "_ship", "replica-ship", "before"),
    ),
    "opentsdb_tpu.tsd.rpcs": (
        ("PutDataPointRpc", "_respond_put", "ingest-ack", "before"),
    ),
    "opentsdb_tpu.tsd.http": (
        ("HttpQuery", "send_reply", "response-write", "after"),
    ),
}

# (cls, method name, original function) for unpatch_all()
_patched: list[tuple[type, str, object]] = []


def configure(enabled: bool) -> None:
    global _enabled
    _enabled = enabled


def reset() -> None:
    with _state_lock:
        _streams.clear()
        _counts.clear()


def snapshot_state() -> tuple:
    """Copy of the accumulated per-stream event logs; fixture tests
    that seed deliberate inversions snapshot/restore around themselves
    so a TSDBSAN=1 session's real streams survive them."""
    with _state_lock:
        return ({k: dict(v) for k, v in _streams.items()},
                dict(_counts))


def restore_state(snapshot: tuple) -> None:
    streams, counts = snapshot
    with _state_lock:
        _streams.clear()
        for k, v in streams.items():
            _streams[k] = dict(v)
        _counts.clear()
        _counts.update(counts)


# --------------------------------------------------------------------- #
# Recording                                                             #
# --------------------------------------------------------------------- #

_obs_trace = None   # resolved lazily; False when the import failed


def _stream_key() -> str:
    global _obs_trace
    if _obs_trace is None:
        try:
            from opentsdb_tpu.obs import trace as obs_trace
            _obs_trace = obs_trace
        except Exception:       # noqa: BLE001 — recording must not raise
            _obs_trace = False
    t = _obs_trace.active() if _obs_trace else None
    if t is not None:
        return "trace:" + t.trace_id
    return "thread:%d" % threading.get_ident()


def record(event: str) -> None:
    """Append `event` to the calling stream's log (first occurrence
    only; repeats just advance the rank counter).  The stack walk for
    the anchor site only happens on first occurrence — this sits on
    the per-append hot path of the sanitized tier-1 run, and the 2x
    overhead pin (tests/test_sanitizer_overhead.py) holds it there."""
    if not _enabled:
        return
    key = _stream_key()
    with _state_lock:
        rank = _counts.get(key, 0)
        _counts[key] = rank + 1
        ev = _streams.setdefault(key, {})
        known = event in ev
    if known:
        return
    path, line, _fn = caller_site()
    with _state_lock:
        ev.setdefault(event, (rank, path, line))


def observed_events() -> set[str]:
    with _state_lock:
        out: set[str] = set()
        for ev in _streams.values():
            out.update(ev)
    return out


def streams() -> dict[str, dict[str, tuple[int, str, int]]]:
    with _state_lock:
        return {k: dict(v) for k, v in _streams.items()}


# --------------------------------------------------------------------- #
# Instrumentation                                                       #
# --------------------------------------------------------------------- #

def instrumented_events() -> set[str]:
    return {entry[2] for entries in PATCH_TABLE.values()
            for entry in entries}


def instrument_module(mod) -> int:
    """Wrap this module's patch-table methods (idempotent).  Returns
    the number of methods newly wrapped; patches are tracked module-
    globally and undone by `unpatch_all()`."""
    entries = PATCH_TABLE.get(getattr(mod, "__name__", ""), ())
    wrapped = 0
    for cls_name, meth, event, when in entries:
        cls = getattr(mod, cls_name, None)
        if not isinstance(cls, type):
            continue
        orig = cls.__dict__.get(meth)
        if orig is None or getattr(orig, "_tsdbsan_order", False):
            continue
        setattr(cls, meth, _wrap(orig, event, when))
        _patched.append((cls, meth, orig))
        wrapped += 1
    return wrapped


def _wrap(orig, event: str, when: str):
    if when == "before":
        def wrapper(*args, **kwargs):
            record(event)
            return orig(*args, **kwargs)
    else:
        def wrapper(*args, **kwargs):
            out = orig(*args, **kwargs)
            record(event)
            return out
    wrapper._tsdbsan_order = True
    wrapper.__name__ = getattr(orig, "__name__", event)
    wrapper.__doc__ = getattr(orig, "__doc__", None)
    return wrapper


def unpatch_all() -> None:
    while _patched:
        cls, meth, orig = _patched.pop()
        setattr(cls, meth, orig)


# --------------------------------------------------------------------- #
# Static <-> dynamic cross-check                                        #
# --------------------------------------------------------------------- #

def static_table_cached() -> dict:
    """The lint's {contracts, events} table, computed at most once per
    process (the tree walk is ~2s — fine at session finish, not per
    test)."""
    global _static_table
    if _static_table is None:
        from tools.lint.ordering import static_order_table
        _static_table = static_order_table()
    return _static_table


def cross_check(static_table: dict | None = None,
                reporter=None) -> dict[str, list]:
    """Diff recorded streams against the declared contracts.  Emits
    note-level findings (into `reporter`, default the process-global
    one) and returns the diff for callers that render it themselves.
    A session that recorded nothing returns empty WITHOUT walking the
    tree for the static table."""
    local = streams()
    if not local:
        return {"violations": [], "gaps": []}
    if static_table is None:
        static_table = static_table_cached()
    rep = reporter if reporter is not None else REPORTER
    contracts = sorted(static_table.get("contracts", ()))
    violations: list[tuple[str, str, str]] = []
    for a, b in contracts:
        for key in sorted(local):
            ev = local[key]
            if a in ev and b in ev and ev[b][0] < ev[a][0]:
                _rank, path, line = ev[b]
                rep.add(
                    path, line, "san-order-violation",
                    "a runtime stream emitted '%s' before '%s' — the "
                    "declared contract '%s before %s' was violated on "
                    "a real execution the static verifier did not "
                    "derive (unannotated call path, or the reorder "
                    "lives outside the lint's scope)" % (b, a, a, b))
                violations.append((key, a, b))
    observed = set()
    for ev in local.values():
        observed.update(ev)
    instr = instrumented_events()
    gaps: list[str] = []
    for name in sorted({n for c in contracts for n in c}):
        if name in instr and name not in observed:
            rep.add(
                "<runtime>", 0, "san-order-gap",
                "contracted order event '%s' is instrumented but was "
                "never observed this session — uncovered path, or the "
                "tagged site moved away from its probe" % name)
            gaps.append(name)
    return {"violations": violations, "gaps": gaps}
