"""pytest plugin: arm tsdbsan for the whole test session.

Loaded by tests/conftest.py when `TSDBSAN=1` (see the `pytest_plugins`
hook there).  The lockset, deadlock, and ordering detectors run for
every test;
the JAX compile/sync sanitizer stays OFF under pytest by default —
tests compile kernels throughout, so warmup/steady phases are
meaningless session-wide; the steady-state serving check
(tests/test_sanitizer_steady.py) and the daemon mode own that detector.

Environment knobs (all optional):

  TSDBSAN=1             arm (read by tests/conftest.py)
  TSDBSAN_REPORT=path   write findings JSON (or SARIF when the path
                        ends in .sarif) at session finish
  TSDBSAN_STATE=path    persist the observed lock-order graph for the
                        offline static<->dynamic cross-check
                        (tools/sanitize/run.py --cross-check)
  TSDBSAN_JAX=1         enable the JAX detector under pytest anyway
  TSDBSAN_WATCHDOG_MS   deadlock watchdog period (default 200)

Error-level findings fail the session (exit status 3) even when every
test passed — a green suite with a detected race is not green.
"""

from __future__ import annotations

import os


def pytest_configure(config) -> None:
    from tools import sanitize
    sanitize.install(
        lockset=True,
        deadlock_watch=True,
        jax=os.environ.get("TSDBSAN_JAX", "") == "1",
        watchdog_ms=int(os.environ.get("TSDBSAN_WATCHDOG_MS", "200")),
        extra_lock_prefixes=("san_fixtures",),
    )


def pytest_sessionfinish(session, exitstatus) -> None:
    from tools.sanitize import deadlock, effects, order
    from tools.sanitize.report import REPORTER
    deadlock.detect_inversions()
    # note-level: acquires that outwaited their ambient request
    # deadline, cross-referenced against the static request-path set
    # (no-op — and no lint tree walk — when nothing was recorded)
    deadlock.report_blocked_past_deadline()
    # note-level: recorded event streams vs the declared happens-before
    # contracts (same no-op guarantee when nothing was recorded)
    order.cross_check()
    # note-level: armed explain-request events vs the static # effects:
    # contract table (same no-op guarantee)
    effects.cross_check()
    state_path = os.environ.get("TSDBSAN_STATE", "")
    if state_path:
        deadlock.save_observed(state_path)
    report_path = os.environ.get("TSDBSAN_REPORT", "")
    if report_path:
        REPORTER.write_report(report_path)
    if REPORTER.errors() and exitstatus == 0:
        session.exitstatus = 3


def pytest_terminal_summary(terminalreporter) -> None:
    from tools.sanitize.report import REPORTER, rule_level
    findings = REPORTER.findings()
    if not findings:
        terminalreporter.write_line("tsdbsan: clean")
        return
    terminalreporter.write_sep("=", "tsdbsan findings")
    for f in findings:
        terminalreporter.write_line(
            "%s: %s" % (rule_level(f.rule), f.render()))
    errors = sum(1 for f in findings if rule_level(f.rule) == "error")
    if errors:
        terminalreporter.write_line(
            "tsdbsan: %d error-level finding(s) — session fails" % errors)
