"""tsdbsan findings: collection, suppression, SARIF.

Reuses tools/lint's Finding shape (path, line, rule, line-number-free
message) so sanitizer findings ride the same baseline/SARIF/suppression
machinery as lint findings.  Rules are leveled: "error" rules gate the
sanitized run; "note" rules are the static<->dynamic cross-check
reports, which are informational by design (an unobserved static edge
usually just means the path was not covered this session).
"""

from __future__ import annotations

import json
import os
import sys
import threading

from tools.lint.core import REPO_ROOT, Finding, SourceFile

# captured before tools/sanitize/locks.py ever patches the factories:
# the reporter's own lock must always be a REAL lock
_RealLock = threading.Lock

# rule -> (level, short description).  Levels follow SARIF: "error"
# findings fail the sanitized run; "note" findings are cross-check
# reports.
SAN_RULES: dict[str, tuple[str, str]] = {
    "san-unguarded-mutation": (
        "error", "Guarded-by-annotated attribute mutated at runtime "
                 "without its declared lock held"),
    "san-lockset-race": (
        "error", "Unannotated attribute written by multiple threads "
                 "with no common lock (Eraser lockset)"),
    "san-lock-order-inversion": (
        "error", "Runtime lock acquisition order forms a cycle"),
    "san-deadlock": (
        "error", "Live wait-for cycle between threads observed"),
    "san-recompile-after-warmup": (
        "error", "Jitted kernel compiled again after the warmup phase"),
    "san-host-sync": (
        "error", "Device->host transfer outside sanctioned sites "
                 "during steady state"),
    "san-stale-static-edge": (
        "note", "Static lock-order edge never observed at runtime "
                "(stale annotation or uncovered path)"),
    "san-lint-gap": (
        "note", "Runtime lock-order edge not derivable statically "
                "(lint gap)"),
    "san-blocked-past-deadline": (
        "note", "Instrumented lock acquire kept waiting past the "
                "ambient request deadline's remainder"),
    "san-order-violation": (
        "note", "Declared happens-before contract violated by a "
                "recorded runtime event stream"),
    "san-order-gap": (
        "note", "Contracted order event instrumented but never "
                "observed this session"),
    "san-effect-violation": (
        "note", "Explain-tagged request had a runtime effect outside "
                "the static # effects: contract"),
}

ERROR_RULES = frozenset(r for r, (lv, _d) in SAN_RULES.items()
                        if lv == "error")


def rule_level(rule: str) -> str:
    return SAN_RULES.get(rule, ("error", ""))[0]


def rel_path(abspath: str, root: str = REPO_ROOT) -> str:
    try:
        rel = os.path.relpath(abspath, root)
    except ValueError:
        return abspath.replace(os.sep, "/")
    if rel.startswith(".."):
        return abspath.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


_SKIP_DIRS = (os.sep + "tools" + os.sep + "sanitize" + os.sep,)
# obs/jaxprof.py hosts the shared compile-log capture the sanitizer
# subscribes through — its dispatch frames are machinery, not the site
# that triggered the compile
_SKIP_MODULES = ("threading.py", "logging/__init__.py",
                 "obs/jaxprof.py")


def caller_site(skip: int = 0) -> tuple[str, int, str]:
    """(repo-relative path, line, function) of the nearest stack frame
    that belongs to the repo and is not sanitizer machinery — the site a
    runtime finding anchors to."""
    f = sys._getframe(1 + skip)
    fallback: tuple[str, int, str] | None = None
    while f is not None:
        fn = f.f_code.co_filename
        if not any(d in fn for d in _SKIP_DIRS) \
                and not fn.endswith(_SKIP_MODULES):
            if fallback is None:
                fallback = (rel_path(fn), f.f_lineno, f.f_code.co_name)
            if os.path.abspath(fn).startswith(REPO_ROOT + os.sep):
                return (rel_path(fn), f.f_lineno, f.f_code.co_name)
        f = f.f_back
    return fallback or ("<unknown>", 0, "<unknown>")


class SanReporter:
    """Process-global, thread-safe findings collector.

    Dedup is by (path, rule, message) — the lint fingerprint — so a racy
    loop reports once, not ten thousand times.  `findings()` applies the
    shared `# tsdblint: disable=<rule>` suppression syntax by re-reading
    the flagged source line (a suppressed finding is a visible,
    reviewable act exactly as it is for lint)."""

    def __init__(self) -> None:
        self._lock = _RealLock()
        self._findings: dict[tuple[str, str, str], Finding] = {}

    def add(self, path: str, line: int, rule: str, message: str) -> None:
        f = Finding(path, line, rule, message)
        with self._lock:
            self._findings.setdefault(f.fingerprint, f)

    def clear(self) -> None:
        with self._lock:
            self._findings.clear()

    def restore(self, findings: list[Finding]) -> None:
        """Re-seed previously snapshotted findings (test isolation)."""
        with self._lock:
            for f in findings:
                self._findings.setdefault(f.fingerprint, f)

    def raw_findings(self) -> list[Finding]:
        with self._lock:
            out = list(self._findings.values())
        return sorted(out, key=lambda f: (f.path, f.line, f.rule, f.message))

    def findings(self, root: str = REPO_ROOT,
                 apply_suppressions: bool = True) -> list[Finding]:
        out = self.raw_findings()
        if not apply_suppressions:
            return out
        cache: dict[str, SourceFile | None] = {}
        kept = []
        for f in out:
            src = _source_for(f.path, root, cache)
            if src is not None and src.suppressed(f.line, f.rule):
                continue
            kept.append(f)
        return kept

    def errors(self, root: str = REPO_ROOT) -> list[Finding]:
        return [f for f in self.findings(root)
                if rule_level(f.rule) == "error"]

    def render(self, root: str = REPO_ROOT) -> str:
        lines = []
        for f in self.findings(root):
            lines.append("%s: %s" % (rule_level(f.rule), f.render()))
        return "\n".join(lines)

    # -- artifacts --

    def to_sarif(self, root: str = REPO_ROOT) -> dict:
        from tools.lint.sarif import to_sarif
        findings = self.findings(root)
        levels = {f.fingerprint: rule_level(f.rule) for f in findings}
        return to_sarif(findings, [_SanRuleSet()], tool_name="tsdbsan",
                        levels=levels)

    def to_json(self, root: str = REPO_ROOT) -> list[dict]:
        return [{"path": f.path, "line": f.line, "rule": f.rule,
                 "level": rule_level(f.rule), "message": f.message}
                for f in self.findings(root)]

    def write_report(self, path: str, root: str = REPO_ROOT) -> None:
        """JSON findings dump (SARIF when the path ends .sarif)."""
        payload = self.to_sarif(root) if path.endswith(".sarif") \
            else self.to_json(root)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
            fh.write("\n")


class _SanRuleSet:
    """Analyzer-shaped shim so sarif.to_sarif can list tsdbsan's rules."""
    name = "tsdbsan"
    rules = tuple(sorted(SAN_RULES))


def _source_for(path: str, root: str,
                cache: dict[str, SourceFile | None]) -> SourceFile | None:
    if path not in cache:
        abspath = os.path.join(root, path)
        try:
            cache[path] = SourceFile(abspath, path)
        except (OSError, SyntaxError, UnicodeDecodeError):
            cache[path] = None
    return cache[path]


REPORTER = SanReporter()
