#!/usr/bin/env python3
"""tsdbsan CLI — one-shot sanitized runs + static<->dynamic cross-check.

    python tools/sanitize/run.py --subset tier1       # sanitized subset
    python tools/sanitize/run.py --subset tier1 --sarif out.sarif
    python tools/sanitize/run.py --cross-check state.json
    python tools/sanitize/run.py --subset tier1 --strict-tests

`--subset tier1` runs the sanitized tier-1 subset (the concurrency-
bearing test files) under `TSDBSAN=1` in a child pytest, collects the
findings report + the observed lock-order graph, then cross-checks the
observed graph against lock_discipline's static one.  Exit status:

    0  zero error-level sanitizer findings (cross-check notes —
       san-stale-static-edge / san-lint-gap / san-blocked-past-deadline
       — and pre-existing test failures do not fail the run)
    1  error-level findings (races / inversions / deadlocks / ...)
    2  usage or harness error

Pass `--strict-tests` to ALSO fail on child test failures (CI that has
a green baseline wants this; containers with known-failing mesh tests
do not).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# The sanitized tier-1 subset: every test file that exercises the
# threaded serving stack.  test_parallel.py rides along for the mesh
# kernels where the environment can import them (collection errors are
# tolerated exactly like tier-1's --continue-on-collection-errors).
SUBSET_TIER1 = [
    "tests/test_concurrency.py",
    "tests/test_cluster_serving.py",
    "tests/test_admission.py",
    "tests/test_batcher.py",
    "tests/test_flightrec.py",
    "tests/test_explain.py",
    "tests/test_agg_cache.py",
    "tests/test_rollup_lanes.py",
    "tests/test_tsd_server.py",
    "tests/test_replication.py",
    "tests/test_parallel.py",
    "tests/test_native_engine.py",
    "tests/test_sanitizer.py",
    "tests/test_sanitizer_steady.py",
]


def run_subset(subset: list[str], sarif: str | None, report: str | None,
               strict_tests: bool) -> int:
    tmpdir = tempfile.mkdtemp(prefix="tsdbsan_")
    # the gate always reads its own JSON artifact; a user --report is
    # written separately afterwards (so --report foo.sarif cannot blind
    # the gate to its own findings)
    report_path = os.path.join(tmpdir, "findings.json")
    state_path = os.path.join(tmpdir, "observed.json")
    env = dict(os.environ)
    env.update({
        "TSDBSAN": "1",
        "TSDBSAN_REPORT": report_path,
        "TSDBSAN_STATE": state_path,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    cmd = [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
           "--continue-on-collection-errors", "-p", "no:cacheprovider",
           *subset]
    print("tsdbsan: running sanitized subset: %s" % " ".join(subset),
          flush=True)
    proc = subprocess.run(cmd, cwd=_REPO, env=env)
    if not os.path.exists(report_path):
        # the child died before pytest_sessionfinish could write the
        # report — a crashed sanitized run must NOT read as clean
        # (chaos_soak.check_san_reports holds the same line)
        print("tsdbsan: findings report %s was never written (child "
              "exited %d) — cannot certify the run" %
              (report_path, proc.returncode))
        return 2
    findings = _load_report(report_path)
    errors = [f for f in findings if f.get("level") == "error"]
    notes = [f for f in findings if f.get("level") != "error"]

    if os.path.exists(state_path):
        print("tsdbsan: cross-checking observed lock-order graph "
              "against the static one", flush=True)
        notes.extend(cross_check(state_path))

    for f in errors:
        print("error: %(path)s:%(line)d: [%(rule)s] %(message)s" % f)
    for f in notes:
        print("note: %(path)s:%(line)d: [%(rule)s] %(message)s" % f)

    everything = errors + notes         # incl. cross-check notes
    if report:
        with open(report, "w", encoding="utf-8") as fh:
            json.dump(everything, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print("tsdbsan: findings JSON written to %s" % report)
    if sarif:
        _write_sarif(everything, sarif)
        print("tsdbsan: SARIF written to %s" % sarif)

    if errors:
        print("tsdbsan: %d error-level finding(s)" % len(errors))
        return 1
    if strict_tests and proc.returncode not in (0,):
        print("tsdbsan: clean, but the subset exited %d and "
              "--strict-tests is set" % proc.returncode)
        return 1
    print("tsdbsan: clean (%d note(s))" % len(notes))
    return 0


def cross_check(state_path: str) -> list[dict]:
    """Offline static<->dynamic diff from a persisted observed graph."""
    from tools.sanitize import deadlock
    from tools.sanitize.report import SanReporter
    observed = deadlock.load_observed(state_path)
    static = deadlock.static_edges_with_sites()
    # a private reporter so the CLI never pollutes the process-global one
    reporter = SanReporter()
    deadlock.cross_check(static_edges=static, observed=observed,
                         reporter=reporter)
    return reporter.to_json()


def _load_report(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    return payload if isinstance(payload, list) else []


def _write_sarif(findings: list[dict], path: str) -> None:
    """One serializer: seed a private SanReporter and reuse its
    to_sarif, so the CLI artifact cannot drift from the plugin's."""
    from tools.lint.core import Finding
    from tools.sanitize.report import SanReporter
    rep = SanReporter()
    rep.restore([Finding(f["path"], f["line"], f["rule"], f["message"])
                 for f in findings])
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(rep.to_sarif(), fh, indent=1, sort_keys=True)
        fh.write("\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tsdbsan", description=__doc__)
    ap.add_argument("--subset", choices=["tier1"],
                    help="run a named sanitized test subset")
    ap.add_argument("--cross-check", metavar="STATE_JSON",
                    help="diff a persisted observed lock-order graph "
                         "against the static one and exit")
    ap.add_argument("--sarif", metavar="PATH",
                    help="write findings as SARIF 2.1.0")
    ap.add_argument("--report", metavar="PATH",
                    help="write findings JSON to this path")
    ap.add_argument("--strict-tests", action="store_true",
                    help="also fail when the child pytest run fails")
    args = ap.parse_args(argv)

    if args.cross_check:
        notes = cross_check(args.cross_check)
        for f in notes:
            print("note: %(path)s:%(line)d: [%(rule)s] %(message)s" % f)
        print("tsdbsan cross-check: %d stale-edge/lint-gap note(s)"
              % len(notes))
        return 0
    if args.subset == "tier1":
        return run_subset(SUBSET_TIER1, args.sarif, args.report,
                          args.strict_tests)
    ap.print_usage()
    return 2


if __name__ == "__main__":
    sys.exit(main())
