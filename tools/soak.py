"""Live-server soak: concurrent HTTP + telnet writers vs readers.

Spins the real asyncio daemon and hammers it for --seconds with mixed
load, then asserts ZERO write loss (every acknowledged point is in the
store) and zero errors.  The reference's scale claim is qualitative
(README:12-15, "tens of thousands of hosts ... every few seconds");
this is the repeatable harness for ours:

    python tools/soak.py [--seconds 90] [--port 14247]
"""

import argparse
import os, json, threading, time, asyncio, socket, urllib.request, urllib.error

_ap = argparse.ArgumentParser()
_ap.add_argument("--seconds", type=int, default=90)
_ap.add_argument("--port", type=int, default=14247)
_args = _ap.parse_args()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from opentsdb_tpu.core import TSDB
from opentsdb_tpu.utils.config import Config
from opentsdb_tpu.tsd.server import TSDServer

tsdb = TSDB(Config({"tsd.core.auto_create_metrics": True}))
tsdb.start_maintenance()
srv = TSDServer(tsdb, port=_args.port, bind="127.0.0.1")
threading.Thread(target=lambda: asyncio.run(srv.serve_forever()),
                 daemon=True).start()
time.sleep(1.2)
B = "http://127.0.0.1:%d" % _args.port
BASE = 1356998400
stop = time.time() + _args.seconds
errors = []
sent_http = [0]
sent_tel = [0]

def http_writer(tid):
    i = 0
    while time.time() < stop:
        i += 1
        body = json.dumps([
            {"metric": "soak.h", "timestamp": BASE + (i * 50 + k),
             "value": k, "tags": {"host": "w%d" % tid}}
            for k in range(50)]).encode()
        r = urllib.request.Request(B + "/api/put", data=body,
                                   headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(r, timeout=30) as resp:
                assert resp.status == 204
            sent_http[0] += 50
        except Exception as e:
            errors.append(("http_put", e)); return

def telnet_writer(tid):
    try:
        s = socket.create_connection(("127.0.0.1", _args.port), timeout=30)
        i = 0
        while time.time() < stop:
            i += 1
            lines = b"".join(
                b"put soak.t %d %d host=t%d\n" % (BASE + i * 50 + k, k, tid)
                for k in range(50))
            s.sendall(lines)
            sent_tel[0] += 50
            time.sleep(0.002)
        s.close()
    except Exception as e:
        errors.append(("telnet_put", e))

def reader():
    while time.time() < stop:
        try:
            with urllib.request.urlopen(
                    B + "/api/query?start=%d&m=sum:1m-count:soak.h%%7Bhost=*%%7D"
                    % BASE, timeout=180) as resp:
                json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code != 400:   # no data yet is fine early
                errors.append(("query", e.code)); return
        except Exception as e:
            errors.append(("query", e)); return
        time.sleep(0.05)

threads = ([threading.Thread(target=http_writer, args=(t,)) for t in range(3)]
           + [threading.Thread(target=telnet_writer, args=(t,)) for t in range(2)]
           + [threading.Thread(target=reader) for _ in range(2)])
for t in threads: t.start()
for t in threads: t.join(150)
time.sleep(2)
stored_h = sum(len(s) for s in tsdb.store.series_for_metric(
    tsdb.metrics.get_id("soak.h")))
stored_t = sum(len(s) for s in tsdb.store.series_for_metric(
    tsdb.metrics.get_id("soak.t")))
print("errors:", errors[:3] if errors else "none")
print("http sent=%d stored=%d; telnet sent=%d stored=%d"
      % (sent_http[0], stored_h, sent_tel[0], stored_t))
stats = tsdb.collect_stats()
print("cache:", {k.split(".")[-1]: v for k, v in stats.items()
                 if "device_cache" in k})
assert not errors
assert stored_h == sent_http[0]
# telnet is fire-and-forget: allow in-flight tail at stop time
assert stored_t >= sent_tel[0] * 0.98, (stored_t, sent_tel[0])
print("SOAK OK")
