"""Stage-decomposition bench: attribute the headline dispatch's time.

The full production dispatch (bench.py shape: 1024x65536, avg-1h, 100
groups) runs ~0.59s on the chip while its theoretical bandwidth cost is
~10ms — ~300x gap that neither precision (f32 saves 8%) nor scan form
(flat vs blocked within 5%) explains.  This bench times each pipeline
stage as its own jitted dispatch, plus raw primitives as bandwidth
yardsticks, using bench.py's honest drain methodology:

    python tools/stage_bench.py

Prints one JSON line per stage.  Stage sum > full-pipeline time is
expected (XLA fuses across stage boundaries in the real program); the
value is the RANKING — whichever stage dominates is the rework target.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import bench
from bench import (_OriginSequence, build_spec, drain, make_batch,
                   measure_rtt, _median, S, N, INTERVAL_MS)


def _note(msg: str) -> None:
    print("[stages] " + msg, file=sys.stderr, flush=True)


def time_fn(fn, args, rtt, reps=3):
    """Median drained time of fn(*args) with the sync cost removed.

    The drain is one serial tunnel round-trip PER OUTPUT LEAF (~70ms each
    on axon), so the subtracted cost is measured against THIS stage's own
    already-computed output — a shared one-leaf probe would bill 1-2
    whole RTTs as chip time on every multi-leaf stage and distort the
    ranking this tool exists to produce.  `rtt` is kept as a floor for
    degenerate cases (a drain can never cost less than one round-trip)."""
    out = fn(*args)
    drain(out)                  # compile
    sync = max(measure_rtt(template=out), rtt)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        drain(fn(*args))
        times.append(max(time.perf_counter() - t0 - sync, 1e-9))
    return _median(times)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from opentsdb_tpu.ops import downsample as ds

    # Stages time EXPLICIT kernel forms; the platform guard would demote
    # the dense search forms on a CPU dev box and mislabel the rows (a
    # no-op on the chip).
    ds.set_platform_mode_guard(False)

    # Fail fast if the tunnel died since the previous stage (a hung
    # dial burns the whole recovery window otherwise).
    bench.guard_backend_init()

    batch = make_batch()
    _note("batch resident")
    spec, wargs, g_pad = build_spec()
    origins = _OriginSequence()
    rtt = measure_rtt()
    _note("rtt %.4fs" % rtt)
    ts, val, mask, gid = batch
    window_spec = spec.downsample.window_spec
    w = window_spec.count

    # Host-computed fixtures reused by isolated stages
    first = wargs["first"]
    cts, cedges = jax.jit(lambda t: ds._compact_ts(t, window_spec, wargs))(ts)
    idx = jax.jit(lambda t, e: jax.vmap(
        lambda row: jnp.searchsorted(row, e, side="left"))(t))(cts, cedges)
    drain((cts, cedges, idx))

    recorded: dict[str, float] = {}

    def record(name, t, points=None):
        # one JSON line per stage, emitted IMMEDIATELY: a chip crash in a
        # later stage must not lose earlier attributions (the reason this
        # tool exists)
        pts = S * N if points is None else points
        recorded[name] = t
        print(json.dumps({"stage": name, "seconds": round(t, 4),
                          "dp_per_sec": round(pts / t, 1)}), flush=True)
        _note("%s: %.4fs" % (name, t))

    # raw primitives: bandwidth yardsticks
    record("prim_f64_mul", time_fn(
        jax.jit(lambda v: v * 1.000001), (val,), rtt))
    record("prim_f64_cumsum", time_fn(
        jax.jit(lambda v: jnp.cumsum(v, axis=1)), (val,), rtt))
    record("prim_f32_cumsum", time_fn(
        jax.jit(lambda v: jnp.cumsum(v.astype(jnp.float32), axis=1)),
        (val,), rtt))
    record("prim_i64_sub", time_fn(
        jax.jit(lambda t: t - first), (ts,), rtt))
    record("prim_gather_edges", time_fn(
        jax.jit(lambda c, i: jnp.take_along_axis(c, i, axis=1)),
        (jnp.cumsum(val, axis=1), jnp.clip(idx, 0, N - 1)), rtt))

    # pipeline stages in production order
    record("compact_ts", time_fn(
        jax.jit(lambda t: ds._compact_ts(t, window_spec, wargs)), (ts,), rtt))
    record("searchsorted", time_fn(
        jax.jit(lambda t, e: jax.vmap(
            lambda row: jnp.searchsorted(row, e, side="left"))(t)),
        (cts, cedges), rtt))

    # r4 attribution-driven forms, timed beside the originals
    import contextlib

    @contextlib.contextmanager
    def forced_mode(module, attr, value):
        """Trace-time module-global kernel-mode swap with restore (the
        modes are read when jit traces, inside the with-block)."""
        prev = getattr(module, attr)
        setattr(module, attr, value)
        try:
            yield
        finally:
            setattr(module, attr, prev)

    def search_hier(t, e):
        with forced_mode(ds, "_SEARCH_MODE", "hier"):
            return ds._edge_search(t, e)

    record("searchsorted_hier", time_fn(
        jax.jit(search_hier), (cts, cedges), rtt))

    def windowed_avg(v, m, i):
        builder = ds._edge_prefix_builder(S, N, i)
        ok = m & ~jnp.isnan(v)
        count = builder(ok.astype(jnp.int32))
        total = builder(jnp.where(ok, v, 0.0))
        return total / jnp.maximum(count, 1)

    record("windowed_avg_given_idx", time_fn(
        jax.jit(windowed_avg), (val, mask, idx), rtt))

    def windowed_avg_subblock(v, m, i):
        builder = ds._edge_subblock_builder(S, N, i)
        ok = m & ~jnp.isnan(v)
        count = builder(ok.astype(jnp.int32))
        total = builder(jnp.where(ok, v, 0.0))
        return total / jnp.maximum(count, 1)

    record("windowed_avg_subblock", time_fn(
        jax.jit(windowed_avg_subblock), (val, mask, idx), rtt))

    # Decompose the subblock windowed-sum (88ms r04b, the biggest
    # accurately-measured single stage): the [S, nb, K] tree reduce vs
    # the tiny cumsum vs the [S, W+1, K] boundary gather + masked dot.
    # Bandwidth yardstick: prim_f64_mul touches the same 537MB in ~18ms,
    # so whichever row exceeds that is compute/serialization, not HBM.
    k_sub = ds._SUB_K
    nb = N // k_sub
    reduce_fn = jax.jit(lambda v: v.reshape(S, nb, k_sub).sum(axis=2))
    record("subblock_reduce", time_fn(reduce_fn, (val,), rtt))
    ssum0 = reduce_fn(val)
    drain((ssum0,))
    record("subblock_cumsum", time_fn(
        jax.jit(lambda x: jnp.cumsum(x, axis=1)), (ssum0,), rtt))

    def subblock_remainder(v, i):
        blk = i // k_sub
        off = i - blk * k_sub
        safe_blk = jnp.clip(blk, 0, nb - 1)
        d3 = v.reshape(S, nb, k_sub)
        bvals = jnp.take_along_axis(d3, safe_blk[:, :, None], axis=1)
        lanes = jnp.arange(k_sub, dtype=off.dtype)
        return jnp.where(lanes[None, None, :] < off[:, :, None],
                         bvals, 0).sum(axis=2)

    record("subblock_remainder", time_fn(
        jax.jit(subblock_remainder), (val, idx), rtt))

    def full_downsample(t, v, m):
        return ds.downsample(t, v, m, "avg", window_spec, wargs)

    record("downsample_full", time_fn(
        jax.jit(full_downsample), (ts, val, mask), rtt))

    from opentsdb_tpu.ops.group_agg import grid_group_aggregate
    from opentsdb_tpu.ops.aggregators import get_agg
    wts0, dval, dmask = jax.jit(full_downsample)(ts, val, mask)
    drain((wts0, dval, dmask))
    agg_sum = get_agg("sum")
    record("group_tail", time_fn(
        jax.jit(lambda g, v, m, gi: grid_group_aggregate(
            g, v, m, gi, g_pad, agg_sum)),
        (wts0, dval, dmask, jnp.asarray(gid)), rtt))

    from opentsdb_tpu.ops import group_agg as ga

    def group_tail_sorted(g, v, m, gi):
        with forced_mode(ga, "_GROUP_REDUCE_MODE", "sorted"):
            return grid_group_aggregate(g, v, m, gi, g_pad, agg_sum)

    record("group_tail_sorted", time_fn(
        jax.jit(group_tail_sorted), (wts0, dval, dmask, jnp.asarray(gid)),
        rtt))

    # Decompose the group tail (~180ms measured r04b on [1024, 512]
    # grids whose raw traffic is ~2MB — three orders of magnitude above
    # bandwidth cost; these rows find where it actually goes):
    # interpolation machinery vs each reduce mode vs the raw reset-scan
    # primitive the sorted mode leans on.
    from opentsdb_tpu.ops.group_agg import (grid_contributions,
                                            moment_group_reduce,
                                            _SortedGroups)
    gid_arr = jnp.asarray(gid)
    # same f64 cast grid_group_aggregate applies before the call — the
    # stage must time the program the pipeline actually runs, including
    # under the single-precision A/B mode
    contrib_fn = jax.jit(lambda g, v, m: grid_contributions(
        g, v.astype(jnp.float64), m, agg_sum))
    record("group_contrib", time_fn(contrib_fn, (wts0, dval, dmask), rtt))
    contrib, participate = contrib_fn(wts0, dval, dmask)
    drain((contrib, participate))

    def reduce_under(mode):
        def run(c, p, gi):
            with forced_mode(ga, "_GROUP_REDUCE_MODE", mode):
                return moment_group_reduce("sum", c, p, gi, g_pad)
        return run

    for mode in ("segment", "matmul", "sorted", "sorted2"):
        record("group_reduce_" + mode, time_fn(
            jax.jit(reduce_under(mode)), (contrib, participate, gid_arr),
            rtt))

    def raw_reset_scan(c, gi):
        sg = _SortedGroups(gi, g_pad, c.shape[0])
        return sg.sum(c.astype(jnp.float64))

    record("group_raw_reset_scan", time_fn(
        jax.jit(raw_reset_scan), (contrib, gid_arr), rtt))

    from bench import dispatch
    record("full_pipeline", time_fn(
        lambda *a: dispatch(spec, g_pad, batch, wargs, origins.next()),
        (), rtt))

    # Streamed chunk fold at the config-2 slice shape: a [128, 65536]
    # chunk against its ~82k-window local slice (W ~ 1.25N).  The
    # _use_segment_chunk threshold routes W > N to segment reductions
    # (TPU scatters serialize) — these rows race that against the dense
    # edge-search form so the threshold gets chip data.
    from opentsdb_tpu.ops import streaming as st
    from opentsdb_tpu.ops.downsample import FixedWindows

    s2, n2 = 128, 65_536
    step2 = 10_000
    start2 = 1_356_998_400_000
    # The production sliced fold runs on an UNPADDED quantized local
    # grid (streaming.quantize_window_slice: 65,538-window chunk span ->
    # wc = 81,920); pow2-padding the spec here (131,072) would measure
    # 2N windows instead of the 1.25N the planner actually dispatches.
    fixed2 = FixedWindows.for_range(start2, start2 + n2 * step2 + step2,
                                    10_000)
    wc2 = st.quantize_window_slice(fixed2.count,
                                   ds.WindowSpec("fixed", 1 << 20,
                                                 10_000))
    wspec2 = ds.WindowSpec("fixed", wc2, 10_000)
    wargs2 = {"first": jnp.asarray(fixed2.first_window_ms, jnp.int64),
              "nwin": jnp.asarray(fixed2.count, jnp.int32)}
    rows2 = jnp.arange(s2, dtype=jnp.int64)
    cols2 = jnp.arange(n2, dtype=jnp.int64)
    h2 = (rows2[:, None] * 2_654_435_761 + cols2[None, :] * 40_503) \
        & 0x7FFFFFFF
    ts2 = start2 + cols2[None, :] * step2 + h2 % 4_000
    val2 = 100.0 + (h2 % 1_000).astype(jnp.float64) * 0.05
    mask2 = jnp.ones((s2, n2), bool)
    drain((ts2, val2, mask2))
    lanes2 = st.lanes_for(["sum", "min", "max", "count"])

    def chunk_segment(t, v, m):
        return st._segment_chunk_moments(t, v, m, wspec2, wargs2, lanes2)

    record("stream_chunk_segment", time_fn(
        jax.jit(chunk_segment), (ts2, val2, mask2), rtt),
        points=s2 * n2)

    def chunk_dense_forced(t, v, m):
        # bypass _use_segment_chunk: same lanes through the edge-search
        # machinery (prefix sums + reset-scan extremes)
        vf, ok, cts_l, idx_l, windowed, cnt = ds._window_scan_setup(
            t, v, m, wspec2, wargs2)
        out = {"n": cnt, "total": windowed(jnp.where(ok, vf, 0.0))}
        lo, hi, _ = ds._extreme_downsample(t, v, m, wspec2, wargs2,
                                           True, True)
        out["lo"], out["hi"] = lo, hi
        return out

    record("stream_chunk_dense", time_fn(
        jax.jit(chunk_dense_forced), (ts2, val2, mask2), rtt),
        points=s2 * n2)

    # FULL production sliced update at the config-2 shape — chunk
    # moments PLUS the donated-state slice merge, dynamic_update_slice
    # write-back, and oob audit the chunk rows above exclude.  If config
    # 2's observed per-chunk cost exceeds the winning chunk-moments row,
    # the difference lives here.  State is threaded (donation consumes
    # the input buffers), so each rep folds into the previous rep's
    # state exactly like the production loop.
    try:
        full_spec = ds.WindowSpec("fixed", 1 << 20, 10_000)
        full_wargs = {"first": jnp.asarray(start2 - (1 << 19) * 10_000,
                                           jnp.int64),
                      "nwin": jnp.asarray(1 << 20, jnp.int32)}
        acc2 = st.StreamAccumulator.create(
            s2, full_spec, full_wargs, lanes=lanes2,
            window_slice=fixed2.count)
        w0_mid = 1 << 19
        acc2.update(ts2, val2, mask2, w0=w0_mid)       # compile + warm
        acc2.oob_count()                               # force the queue
        reps, t0 = 3, time.perf_counter()
        for _ in range(reps):
            acc2.update(ts2, val2, mask2, w0=w0_mid)
            acc2.oob_count()
        per = (time.perf_counter() - t0) / reps - rtt
        record("stream_sliced_update", per, points=s2 * n2)
    except Exception as e:   # noqa: BLE001 — keep later stages alive
        _note("stream_sliced_update FAILED: %s" % e)

    # ---- cost-model calibration (ops/costmodel.py) -------------------
    # Convert THIS session's stage timings into the per-unit costs the
    # shape-driven mode chooser uses, so auto-selection follows the chip
    # actually measured rather than the hardcoded r4 anchors.  The
    # session runner persists the record to BENCH_CALIBRATION.json.
    # Never emitted on CPU (a smoke run must not masquerade as chip
    # calibration).
    if jax.devices()[0].platform != "cpu":
        import numpy as _np
        e_cnt = int(cedges.shape[0])
        logn = max(int(_np.ceil(_np.log2(max(N, 2)))), 1)
        denoms = {
            "gather_round": ("searchsorted", S * e_cnt * logn),
            "hier_cell": ("searchsorted_hier",
                          S * ((N // 32) + 32) * e_cnt),
            "scan_f64": ("prim_f64_cumsum", S * N),
            "elem_f64": ("prim_f64_mul", S * N),
            "win_gather": ("prim_gather_edges", S * e_cnt),
            "seg_scatter": ("group_reduce_segment", S * w),
            "mxu_cell": ("group_reduce_matmul", g_pad * S * w),
            "sorted_grid": ("group_reduce_sorted", S * w),
            "sorted2_grid": ("group_reduce_sorted2", S * w),
        }
        costs = {key: recorded[label] / denom
                 for key, (label, denom) in denoms.items()
                 if label in recorded and recorded[label] > 0}
        if costs:
            print(json.dumps({"stage": "calibration",
                              "costs_tpu": {k: float("%.4g" % v)
                                            for k, v in costs.items()}}),
                  flush=True)


if __name__ == "__main__":
    main()
