"""Render a chip-session artifact (BENCH_CONFIGS_rNN.json) as markdown.

    python tools/summarize_session.py [path]

Sections: the bench_prefix race table (sorted by dispatch time, winner
starred), stage attribution, the headline row, per-config BASELINE rows
with vs_baseline, histogram row, and any error/skip rows — the exact
tables NOTES_rNN.md and README report after a session.
"""

from __future__ import annotations

import json
import os
import sys


def load(path: str) -> list[dict]:
    recs = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    recs.append(json.loads(ln))
                except ValueError:
                    pass
    return recs


def main() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        repo, "BENCH_CONFIGS_r05.json")
    recs = load(path)
    if not recs:
        print("no records in %s" % path)
        return

    prefix = [r for r in recs if r.get("stage") == "bench_prefix"
              and "s_per_dispatch" in r]
    if prefix:
        print("## bench_prefix race (%d rows)\n" % len(prefix))
        print("| config | s/dispatch | dp/s |")
        print("|---|---|---|")
        best = min(r["s_per_dispatch"] for r in prefix)
        for r in sorted(prefix, key=lambda r: r["s_per_dispatch"]):
            star = " **<- winner**" if r["s_per_dispatch"] == best else ""
            print("| %s%s | %.4f | %.1fM |"
                  % (r["config"], star, r["s_per_dispatch"],
                     r.get("dp_per_sec", 0) / 1e6))
        print()

    stages = [r for r in recs if r.get("stage") == "stage_bench"
              and "seconds" in r]
    if stages:
        print("## stage attribution\n")
        print("| stage | ms | dp/s |")
        print("|---|---|---|")
        for r in stages:
            print("| %s | %.1f | %.1fM |"
                  % (r.get("label", "?"), r["seconds"] * 1e3,
                     r.get("dp_per_sec", 0) / 1e6))
        print()
    cal = [r for r in recs if r.get("label") == "calibration"]
    if cal:
        print("calibration written: %s\n"
              % json.dumps(cal[-1].get("costs_tpu", {})))

    bench = [r for r in recs if r.get("stage") == "bench"
             and "vs_baseline" in r]
    for r in bench:
        if r.get("skipped"):
            print("## headline: SKIPPED — %s\n" % r.get("reason"))
        else:
            print("## headline: %.1fM dp/s/chip  (vs_baseline %.2fx)\n"
                  % (r.get("value", 0) / 1e6, r.get("vs_baseline", 0)))

    configs = [r for r in recs
               if str(r.get("stage", "")).startswith("bench_configs")
               and "vs_baseline" in r]
    if configs:
        print("## BASELINE configs\n")
        print("| metric | value | vs_baseline |")
        print("|---|---|---|")
        for r in configs:
            print("| %s | %s %s | %.3fx |"
                  % (r["metric"][:110], r.get("value"),
                     r.get("unit", ""), r.get("vs_baseline", 0)))
        print()

    hist = [r for r in recs if r.get("stage") == "hist_bench"
            and "vs_baseline" in r]
    for r in hist:
        print("## histogram: %s %s  (%.2fx vs numpy reference)\n"
              % (r.get("value"), r.get("unit", ""),
                 r.get("vs_baseline", 0)))

    errors = [r for r in recs if "error" in r]
    if errors:
        print("## errors / skips\n")
        for r in errors:
            print("- %s: %s" % (r.get("stage", r.get("metric", "?")),
                                str(r["error"])[:200]))


if __name__ == "__main__":
    main()
