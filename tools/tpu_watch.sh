#!/bin/bash
# Probe the axon tunnel every ~5 min; on recovery, immediately run the
# follow-up chip session (the stages r05 lost), then keep logging status.
# Log: /tmp/tpu_watch.log   Measurement log: /tmp/chip_measurements.log
cd /root/repo
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 300 python -c "
import jax
ds = jax.devices()
import jax.numpy as jnp
(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()
print('ALIVE', ds)
" 2>&1)
  echo "$ts $(echo "$out" | tail -1)" >> /tmp/tpu_watch.log
  if echo "$out" | grep -q ALIVE; then
    # retry until one SUCCESSFUL session (a transient ALIVE must not
    # consume the run), but cap attempts — a deterministic failure must
    # not monopolize the shared chip with back-to-back 8h sessions.
    # Marker holds "ok" after success, else the attempt count.
    state=$(cat /tmp/chip_followup.started 2>/dev/null)
    attempts=${state:-0}
    if [ "$state" = "ok" ]; then
      # done: stop probing entirely — a probe holds the exclusive tunnel
      # for seconds and two JAX processes deadlock it, so an idle watcher
      # must not race the driver's end-of-round bench run
      echo "$ts measurement complete; watcher exiting" >> /tmp/tpu_watch.log
      exit 0
    fi
    if [ "$attempts" -lt 3 ] 2>/dev/null; then
      attempts=$((attempts + 1))
      echo "$attempts" > /tmp/chip_followup.started
      echo "$ts TPU BACK - measurement attempt $attempts" >> /tmp/tpu_watch.log
      timeout 28800 python tools/run_followup_measurements.py \
        > "/tmp/chip_followup.$attempts.log" 2>&1
      rc=$?
      [ "$rc" = "0" ] && echo "ok" > /tmp/chip_followup.started
      echo "$(date -u +%H:%M:%S) measurement attempt $attempts rc=$rc" \
        >> /tmp/tpu_watch.log
    fi
  fi
  sleep 240
done
