#!/bin/bash
# Probe the axon tunnel every ~5 min; on recovery, immediately run the
# full chip measurement session (once), then keep logging status.
# Log: /tmp/tpu_watch.log   Measurement log: /tmp/chip_measurements.log
cd /root/repo
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 300 python -c "
import jax
ds = jax.devices()
import jax.numpy as jnp
(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()
print('ALIVE', ds)
" 2>&1)
  echo "$ts $(echo "$out" | tail -1)" >> /tmp/tpu_watch.log
  if echo "$out" | grep -q ALIVE; then
    # run-once only after a SUCCESSFUL session: a transient ALIVE on the
    # flaky tunnel must not permanently consume the auto-run
    if [ "$(cat /tmp/chip_measurements.started 2>/dev/null)" != "0" ]; then
      echo "$ts TPU BACK - starting measurement session" >> /tmp/tpu_watch.log
      timeout 28800 python tools/run_chip_measurements.py \
        > /tmp/chip_measurements.log 2>&1
      rc=$?
      echo "$rc" > /tmp/chip_measurements.started
      echo "$(date -u +%H:%M:%S) measurement session rc=$rc" >> /tmp/tpu_watch.log
    fi
  fi
  sleep 240
done
