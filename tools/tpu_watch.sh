#!/bin/bash
# Probe the axon tunnel every ~4 min; on recovery, run the follow-up
# chip session (the stages r05 lost).  The runner resumes across
# attempts (/tmp/chip_followup.done) and exits nonzero while stages
# remain unmeasured, so short tunnel windows accumulate coverage.
# Hard stops: 6 attempts (resume + the 240s init watchdog make a false
# window cheap), or MAX_WALL_S since launch — an idle probe must never
# race the driver's end-of-round bench for the exclusive tunnel.
# Log: /tmp/tpu_watch.log
cd /root/repo
START_TS=$(date +%s)
MAX_WALL_S=${MAX_WALL_S:-28800}   # 8h
while true; do
  if [ $(($(date +%s) - START_TS)) -ge "$MAX_WALL_S" ]; then
    echo "$(date -u +%H:%M:%S) wall cap reached; watcher exiting" \
      >> /tmp/tpu_watch.log
    exit 0
  fi
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 300 python -c "
import jax
ds = jax.devices()
import jax.numpy as jnp
(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()
print('ALIVE', ds)
" 2>&1)
  echo "$ts $(echo "$out" | tail -1)" >> /tmp/tpu_watch.log
  if echo "$out" | grep -q ALIVE; then
    state=$(cat /tmp/chip_followup.started 2>/dev/null)
    attempts=${state:-0}
    # Fresh arming (no attempt marker): clear any stale resume state
    # from an EARLIER armed session, or the runner would skip its
    # stages and report old rows as freshly measured.  Within one armed
    # session the marker exists, so resume state survives retries.
    [ -f /tmp/chip_followup.started ] || rm -f /tmp/chip_followup.done
    if [ "$state" = "ok" ]; then
      echo "$ts measurement complete; watcher exiting" >> /tmp/tpu_watch.log
      exit 0
    fi
    if [ "$attempts" -lt 6 ] 2>/dev/null; then
      # The wall cap bounds the RUN too, not just the next probe: a
      # session launched near the cap must not hold the exclusive
      # tunnel into the driver's end-of-round bench window.
      remaining=$((MAX_WALL_S - ($(date +%s) - START_TS)))
      if [ "$remaining" -lt 900 ]; then
        echo "$ts tunnel back but <15min of wall budget; watcher exiting" \
          >> /tmp/tpu_watch.log
        exit 0
      fi
      attempts=$((attempts + 1))
      echo "$attempts" > /tmp/chip_followup.started
      echo "$ts TPU BACK - measurement attempt $attempts" >> /tmp/tpu_watch.log
      # Cooperative budget: the runner stops STARTING stages at the
      # deadline and exits cleanly; the hard timeout is a distant
      # backstop (a SIGKILL mid-dispatch on a live tunnel is the known
      # wedge mechanism and would endanger the driver's own bench run).
      SESSION_DEADLINE_UNIX=$(($(date +%s) + remaining)) \
        timeout $((remaining + 1800)) python tools/run_followup_measurements.py \
        > "/tmp/chip_followup.$attempts.log" 2>&1
      rc=$?
      [ "$rc" = "0" ] && echo "ok" > /tmp/chip_followup.started
      echo "$(date -u +%H:%M:%S) measurement attempt $attempts rc=$rc" \
        >> /tmp/tpu_watch.log
    else
      echo "$ts attempt cap reached; watcher exiting" >> /tmp/tpu_watch.log
      exit 0
    fi
  fi
  sleep 240
done
