#!/bin/bash
# Probe the axon TPU tunnel every 5 min; append status to /tmp/tpu_watch.log
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 300 python -c "
import jax
ds = jax.devices()
import jax.numpy as jnp
(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()
print('ALIVE', ds)
" 2>&1 | tail -2)
  echo "$ts $out" >> /tmp/tpu_watch.log
  if echo "$out" | grep -q ALIVE; then
    echo "$ts TPU IS BACK" >> /tmp/tpu_watch.log
  fi
  sleep 240
done
